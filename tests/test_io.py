"""File IO stage (host.io): prefetched stream reading, fallback path,
and the read -> stage -> transfer pipeline composition."""

import os
import subprocess
import sys

import numpy as np
import pytest

from veles.simd_tpu.host import io as hio
from veles.simd_tpu.host.feed import FeedPipeline


@pytest.fixture
def i16_file(tmp_path, rng):
    data = rng.integers(-30000, 30000, size=48_000).astype(np.int16)
    path = tmp_path / "signal.i16"
    path.write_bytes(data.tobytes())
    return path, data


def test_filestream_roundtrip_with_ragged_tail(i16_file, rng):
    path, data = i16_file
    # 48000 int16 = 96000 bytes; 25000-byte chunks -> 3 full + ragged tail
    chunks = []
    with hio.FileStream(path, np.int16, chunk_bytes=25_000) as fs:
        assert fs.file_size == data.nbytes
        for chunk in fs:
            chunks.append(chunk.copy())   # views die at next iteration
    sizes = [len(c) for c in chunks]
    assert sizes == [12_500, 12_500, 12_500, 10_500]
    np.testing.assert_array_equal(np.concatenate(chunks), data)


def test_read_signal_exact_multiple(tmp_path, rng):
    data = rng.normal(size=4096).astype(np.float32)
    path = tmp_path / "sig.f32"
    path.write_bytes(data.tobytes())
    got = hio.read_signal(path, np.float32, chunk_bytes=4096)
    np.testing.assert_array_equal(got, data)


def test_view_lease_is_per_iteration(i16_file):
    path, data = i16_file
    with hio.FileStream(path, np.int16, chunk_bytes=24_000) as fs:
        first = next(fs)
        first_copy = first.copy()
        next(fs)  # invalidates `first`'s buffer lease
        np.testing.assert_array_equal(first_copy, data[:12_000])


def test_file_batches_drops_ragged_tail(i16_file):
    path, data = i16_file
    # copy per iteration: yields are views with a one-iteration lease
    batches = [b.copy() for b in hio.file_batches(path, (5, 2000),
                                                  np.int16)]
    assert len(batches) == 4          # 48000 // 10000, tail 8000 dropped
    for i, b in enumerate(batches):
        assert b.shape == (5, 2000)
        np.testing.assert_array_equal(
            b.ravel(), data[i * 10_000:(i + 1) * 10_000])


def test_feed_pipeline_from_file(i16_file):
    # the full loader: C++ prefetch thread -> staged conversion -> device
    path, data = i16_file
    src = hio.file_batches(path, (5, 2000), np.int16)
    got = []
    with FeedPipeline(src, dtype=np.float32, depth=2) as feed:
        for dev in feed:
            got.append(np.asarray(dev))
    assert len(got) == 4
    want = data[:40_000].astype(np.float32).reshape(4, 5, 2000)
    for i, g in enumerate(got):
        np.testing.assert_array_equal(g, want[i])


def test_errors(tmp_path):
    with pytest.raises(OSError):
        hio.FileStream(tmp_path / "missing.bin", np.int16)
    odd = tmp_path / "odd.bin"
    odd.write_bytes(b"\x00" * 7)      # not a multiple of int16
    with pytest.raises(ValueError, match="multiple"):
        hio.FileStream(odd, np.int16)
    with pytest.raises(ValueError, match="chunk_bytes"):
        hio.FileStream(tmp_path / "x", np.int16, chunk_bytes=3)


def test_fallback_without_native(i16_file):
    path, data = i16_file
    code = (
        "import numpy as np; from veles.simd_tpu.host import io as hio; "
        f"got = hio.read_signal({str(path)!r}, np.int16, "
        "chunk_bytes=25000); "
        "assert not hio._native.available(); "
        f"assert got.nbytes == {data.nbytes}; "
        "print(int(got[:100].sum()))")
    env = dict(os.environ, VELES_NO_NATIVE="1", JAX_PLATFORMS="cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, cwd=repo)
    assert r.returncode == 0, r.stderr
    assert int(r.stdout.strip().splitlines()[-1]) == int(data[:100].sum())


def test_empty_file_yields_nothing(tmp_path):
    p = tmp_path / "empty.bin"
    p.write_bytes(b"")
    with hio.FileStream(p, np.int16, chunk_bytes=4096) as fs:
        assert fs.file_size == 0
        assert list(fs) == []


def test_chunk_larger_than_file(tmp_path, rng):
    data = rng.normal(size=100).astype(np.float32)
    p = tmp_path / "small.f32"
    p.write_bytes(data.tobytes())
    with hio.FileStream(p, np.float32, chunk_bytes=1 << 20) as fs:
        chunks = [c.copy() for c in fs]
    assert len(chunks) == 1
    np.testing.assert_array_equal(chunks[0], data)


def test_next_after_close_is_safe(i16_file):
    # native: close frees the double buffers; a subsequent next must
    # refuse (never hand out a freed pointer) — OSError or StopIteration
    path, _ = i16_file
    fs = hio.FileStream(path, np.int16, chunk_bytes=4096)
    next(fs)
    fs.close()
    with pytest.raises((OSError, StopIteration)):
        next(fs)
