"""Differential matrix tests (tests/matrix.cc:94-204 pattern).

Dimension tuples include odd sizes to exercise the pad-and-slice path that
replaces the reference's scalar tails (tests/matrix.cc:159-204 uses 99 and
125x299x999 for the same reason).
"""

import numpy as np
import pytest

from veles.simd_tpu import ops

SHAPES = [(4, 4, 4), (8, 8, 8), (99, 35, 77), (1, 7, 1), (16, 128, 256),
          (125, 64, 33)]


# The xla impl runs at precision="highest" (full f32 products) -> tight
# bounds. The pallas kernel runs the MXU's native bf16-product/f32-accum
# mode BY DESIGN (pallas/matmul.py); on real TPU hardware that is ~2^-8
# relative per product. The reference's own differential epsilon for this
# op is 0.1 (tests/matrix.cc:94-98 ASSERT_NEAR) — use it for that path.
# (On CPU the pallas interpreter computes f32, passing trivially.)
def _mm_tol(impl):
    if impl == "xla":
        return {"rtol": 2e-5, "atol": 2e-4}
    return {"rtol": 5e-2, "atol": 0.1}


@pytest.mark.parametrize("impl", ["xla", "pallas"])
@pytest.mark.parametrize("h1,w1,w2", SHAPES)
def test_matrix_multiply(impl, h1, w1, w2, rng):
    m1 = rng.normal(size=(h1, w1)).astype(np.float32)
    m2 = rng.normal(size=(w1, w2)).astype(np.float32)
    ref = ops.matrix_multiply(m1, m2, impl="reference")
    kwargs = {"precision": "highest"} if impl == "xla" else {}
    got = np.asarray(ops.matrix_multiply(m1, m2, impl=impl, **kwargs))
    np.testing.assert_allclose(got, ref, **_mm_tol(impl))


@pytest.mark.parametrize("impl", ["xla", "pallas"])
@pytest.mark.parametrize("h1,w1,h2", [(4, 4, 4), (99, 35, 77), (16, 128, 64)])
def test_matrix_multiply_transposed(impl, h1, w1, h2, rng):
    m1 = rng.normal(size=(h1, w1)).astype(np.float32)
    m2 = rng.normal(size=(h2, w1)).astype(np.float32)
    ref = ops.matrix_multiply_transposed(m1, m2, impl="reference")
    kwargs = {"precision": "highest"} if impl == "xla" else {}
    got = np.asarray(ops.matrix_multiply_transposed(m1, m2, impl=impl, **kwargs))
    np.testing.assert_allclose(got, ref, **_mm_tol(impl))
    # identity: multiply_transposed(m1, m2) == multiply(m1, m2.T)
    got2 = np.asarray(ops.matrix_multiply(m1, m2.T, impl=impl, **kwargs))
    np.testing.assert_allclose(got, got2, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_add_sub(impl, rng):
    a = rng.normal(size=(33, 65)).astype(np.float32)
    b = rng.normal(size=(33, 65)).astype(np.float32)
    np.testing.assert_allclose(ops.matrix_add(a, b, impl=impl),
                               ops.matrix_add(a, b, impl="reference"),
                               rtol=1e-6)
    np.testing.assert_allclose(ops.matrix_sub(a, b, impl=impl),
                               ops.matrix_sub(a, b, impl="reference"),
                               rtol=1e-6)


@pytest.mark.parametrize("transpose", [False, True])
def test_pallas_f32_precision_path(transpose, rng):
    """ADVICE r2: impl='pallas' regained an f32-accurate product via
    precision='highest' (full-width operands through the in-kernel dot) —
    pinned at the xla-HIGHEST tolerance, not the bf16 0.1 epsilon."""
    m1 = rng.normal(size=(99, 35)).astype(np.float32)
    m2 = rng.normal(size=(77, 35) if transpose else (35, 77)).astype(
        np.float32)
    fn = (ops.matrix_multiply_transposed if transpose
          else ops.matrix_multiply)
    ref = fn(m1, m2, impl="reference")
    got = np.asarray(fn(m1, m2, impl="pallas", precision="highest"))
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-4)
    with pytest.raises(ValueError):
        fn(m1, m2, impl="pallas", precision="high")


def test_multiply_golden():
    m1 = np.array([[1.0, 2.0], [3.0, 4.0]], dtype=np.float32)
    m2 = np.array([[5.0, 6.0], [7.0, 8.0]], dtype=np.float32)
    np.testing.assert_array_equal(
        np.asarray(ops.matrix_multiply(m1, m2)), [[19, 22], [43, 50]])
    np.testing.assert_array_equal(
        np.asarray(ops.matrix_multiply_transposed(m1, m2)), [[17, 23], [39, 53]])


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_shape_contract(impl):
    with pytest.raises(ValueError):
        ops.matrix_multiply(np.zeros((2, 3), np.float32),
                            np.zeros((2, 3), np.float32), impl=impl)
    with pytest.raises(ValueError):
        ops.matrix_multiply_transposed(np.zeros((2, 3), np.float32),
                                       np.zeros((3, 2), np.float32), impl=impl)
