"""Cross-correlation suite (tests/correlate.cc patterns).

Mirrors the reference's dedicated correlate suite: golden vectors
(correlate.cc:53-71), differential sweeps against the float64 oracle, the
handle API, and the reversed-convolution delegation identity
(correlate.c:128-142).
"""

import os

import numpy as np
import pytest

from veles.simd_tpu import ops

GOLDEN_X = np.array([1, 2, 3, 4, 5, 6, 7, 8], dtype=np.float32)
GOLDEN_H = np.array([10, 9, 8, 7], dtype=np.float32)
GOLDEN_CORR = [7, 22, 46, 80, 114, 148, 182, 216, 187, 142, 80]

SIZES = [(32, 5), (50, 12), (200, 50), (350, 127), (1020, 50), (2000, 512),
         (2000, 950), (333, 77)]


@pytest.mark.parametrize("algorithm", ["direct", "fft"])
def test_correlate_golden(algorithm):
    got = np.asarray(ops.cross_correlate(GOLDEN_X, GOLDEN_H,
                                         algorithm=algorithm))
    np.testing.assert_allclose(got, GOLDEN_CORR, atol=1e-3)


@pytest.mark.parametrize("algorithm", ["direct", "fft", "overlap_save"])
def test_correlate_batched(algorithm, rng):
    """(B, N) through the reversed-h delegation — row i matches the 1-D
    oracle for every algorithm."""
    x_len, h_len = (65536, 127) if algorithm == "overlap_save" else (350, 63)
    batch = rng.normal(size=(3, x_len)).astype(np.float32)
    h = rng.normal(size=h_len).astype(np.float32)
    got = np.asarray(ops.cross_correlate(batch, h, algorithm=algorithm))
    assert got.shape == (3, x_len + h_len - 1)
    for i in range(3):
        ref = ops.cross_correlate(batch[i], h, impl="reference")
        np.testing.assert_allclose(got[i], ref, rtol=2e-4, atol=2e-3)


@pytest.mark.parametrize("x_len,h_len", SIZES)
@pytest.mark.parametrize("algorithm", ["direct", "fft", "overlap_save"])
def test_correlate_differential(x_len, h_len, algorithm, rng):
    if algorithm == "overlap_save" and h_len >= x_len / 2:
        pytest.skip("overlap_save precondition")
    if (algorithm == "direct" and h_len > 512
            and os.environ.get("VELES_TEST_TPU") == "1"):
        # same degenerate-lowering fallback skip as test_convolve
        pytest.skip("degenerate-lowering fallback: CPU-validated only")
    x = rng.normal(size=x_len).astype(np.float32)
    h = rng.normal(size=h_len).astype(np.float32)
    ref = ops.cross_correlate(x, h, impl="reference")
    got = np.asarray(ops.cross_correlate(x, h, algorithm=algorithm))
    assert got.shape == (x_len + h_len - 1,)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-3)


def test_matches_numpy_correlate_full(rng):
    x = rng.normal(size=200).astype(np.float32)
    h = rng.normal(size=31).astype(np.float32)
    want = np.correlate(h.astype(np.float64), x.astype(np.float64),
                        mode="full")[::-1]
    got = np.asarray(ops.cross_correlate(x, h))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-3)


def test_is_reversed_convolution(rng):
    """The delegation identity the whole module is built on
    (correlate.c:37-72): corr(x, h) == conv(x, reverse(h))."""
    x = rng.normal(size=300).astype(np.float32)
    h = rng.normal(size=40).astype(np.float32)
    via_conv = np.asarray(ops.convolve(x, h[::-1].copy(), algorithm="fft"))
    got = np.asarray(ops.cross_correlate(x, h, algorithm="fft"))
    np.testing.assert_allclose(got, via_conv, atol=1e-3)


def test_named_algorithm_wrappers(rng):
    x = rng.normal(size=400).astype(np.float32)
    h = rng.normal(size=25).astype(np.float32)
    ref = ops.cross_correlate(x, h, impl="reference")
    for fn in (ops.cross_correlate_simd, ops.cross_correlate_fft):
        np.testing.assert_allclose(np.asarray(fn(x, h)), ref,
                                   rtol=2e-4, atol=2e-3)
    np.testing.assert_allclose(
        np.asarray(ops.cross_correlate_overlap_save(
            np.tile(x, 64), h)),
        ops.cross_correlate(np.tile(x, 64), h, impl="reference"),
        rtol=5e-4, atol=5e-3)


def test_handle_api(rng):
    x = rng.normal(size=1020).astype(np.float32)
    h = rng.normal(size=50).astype(np.float32)
    handle = ops.cross_correlate_initialize(1020, 50, algorithm="fft")
    assert handle.reverse
    np.testing.assert_allclose(np.asarray(handle(x, h)),
                               ops.cross_correlate(x, h, impl="reference"),
                               rtol=2e-4, atol=2e-3)
    ops.cross_correlate_finalize(handle)  # no-op, parity
    with pytest.raises(ValueError):
        handle(x[:100], h)


def test_autocorrelation_peaks_at_zero_lag(rng):
    x = rng.normal(size=256).astype(np.float32)
    r = np.asarray(ops.cross_correlate(x, x))
    assert r.shape == (511,)
    assert np.argmax(r) == 255  # zero lag sits at index x_len-1
    np.testing.assert_allclose(r[255], float(np.dot(x, x)), rtol=1e-4)


class TestCrossCorrelate2D:
    def test_matches_scipy(self, rng):
        from scipy.signal import correlate2d

        x = rng.normal(size=(9, 12)).astype(np.float32)
        h = rng.normal(size=(3, 4)).astype(np.float32)
        want = correlate2d(x.astype(np.float64), h.astype(np.float64))
        got = np.asarray(ops.cross_correlate2D(x, h))
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_batched_and_fft_leg(self, rng):
        from scipy.signal import correlate2d

        x = rng.normal(size=(2, 16, 16)).astype(np.float32)
        h = rng.normal(size=(5, 5)).astype(np.float32)
        want = np.stack([correlate2d(r.astype(np.float64),
                                     h.astype(np.float64)) for r in x])
        got = np.asarray(ops.cross_correlate2D(x, h, algorithm="fft"))
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)

    def test_autocorrelation_peak_at_center(self, rng):
        """The matched-filter property: cross-correlating a patch with
        itself peaks where they align."""
        h = rng.normal(size=(7, 7)).astype(np.float32)
        got = np.asarray(ops.cross_correlate2D(h, h))
        peak = np.unravel_index(np.argmax(got), got.shape)
        assert peak == (6, 6)


def test_correlate_batch_aware_memory_bound():
    """cross_correlate shares convolve's batch-scaled HBM bound (a
    review pass found the correlate path still batch-blind after the
    convolve fix): the same shape that routes batched convolve off the
    band routes batched correlate too."""
    n, m = 1 << 22, 1024
    assert ops.cross_correlate_initialize(n, m).algorithm == "direct"
    assert ops.cross_correlate_initialize(n, m, batch=64).algorithm == \
        "overlap_save"
