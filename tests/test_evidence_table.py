"""tools/evidence_table.py: the canonical perf table is a FUNCTION of
the bench artifacts (VERDICT r3 weak #4 — three hand-maintained tables
disagreed). Pins: rendering from a record, marker splicing, and that
BASELINE.md actually carries the markers so --update has a target."""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import evidence_table as et  # noqa: E402

RECORD = {
    "metric": "matrix_multiply_f32_n4096", "value": 159074.3,
    "unit": "GFLOPS", "raw_value": 148908.2, "vs_ref_avx": 14409.6,
    "vs_ref_avx_raw": 13488.4, "pallas_gflops": 174936.2,
    "pallas_vs_xla": 1.08, "backend": "tpu", "recorded_unix": 1753000000,
    "cfg_unit": "MSamples/s",
    "configs": {
        "convolve_n65536_m127": {
            "value": 4199.4, "raw_value": 2214.0, "vs_ref_avx": 67.6,
            "vs_ref_avx_raw": 35.7, "vs_ref_fft": 38.0,
            "direct_shift_msps": 4199.4},
        "elementwise_add_mul_scale_n1000000": {
            "value": 1004.6, "raw_value": 176.6, "unit": "Gop/s",
            "floor_dom": True},
        "welch_b64_n16384_nfft512": {
            "value": None, "error": "leg failed"},
    },
}


def test_render_contains_all_configs():
    block = et.render("bench_full_last.json", RECORD)
    assert block.startswith(et.BEGIN) and block.endswith(et.END)
    assert "matrix_multiply_f32_n4096" in block
    assert "4,199" in block and "67.6x" in block.replace("68x", "67.6x") \
        or "68x" in block
    assert "38x" in block                       # FFT proxy ceiling column
    assert "raw 13,488x" in block               # raw floor speedup
    assert "FLOOR-DOMINATED" in block           # the self-labeling marker
    assert "ERROR: leg failed" in block         # nulls never unexplained
    assert "recorded_unix 1753000000" in block  # run provenance cited


def test_splice_roundtrip(tmp_path):
    doc = tmp_path / "doc.md"
    doc.write_text(f"prose above\n{et.BEGIN}\nold table\n{et.END}\nbelow\n")
    block = et.render("x.json", RECORD)
    new = et.splice(str(doc), block)
    assert "old table" not in new
    assert "prose above" in new and "below" in new
    assert new.count(et.BEGIN) == 1 and new.count(et.END) == 1
    # idempotent: splicing the same block again changes nothing
    doc.write_text(new)
    assert et.splice(str(doc), block) == new


def test_baseline_md_carries_markers():
    with open(os.path.join(REPO, "BASELINE.md")) as f:
        text = f.read()
    assert et.BEGIN in text and et.END in text


def test_check_mode_detects_staleness(tmp_path, monkeypatch, capsys):
    doc = tmp_path / "doc.md"
    doc.write_text(f"{et.BEGIN}\nstale\n{et.END}\n")
    rec_path = tmp_path / "rec.json"
    rec_path.write_text(json.dumps(RECORD))
    monkeypatch.setattr(sys, "argv",
                        ["evidence_table.py", "--check",
                         "--bench", str(rec_path),
                         "--targets", str(doc)])
    try:
        et.main()
        raised = False
    except SystemExit as e:
        raised = e.code == 1
    assert raised, "--check must exit 1 on a stale table"
