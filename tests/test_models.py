"""Composed model tests: matched filter finds injected templates, the
denoiser actually denoises, the flagship pipeline jits and batches."""

import jax.numpy as jnp
import numpy as np
import pytest

from veles.simd_tpu.models import (MatchedFilterDetector, SignalPipeline,
                                   WaveletDenoiser)


class TestMatchedFilter:
    def test_finds_injected_template(self, rng):
        n, m = 1024, 31
        t = np.hanning(m).astype(np.float32)
        sig = 0.05 * rng.normal(size=n).astype(np.float32)
        where = [200, 700]
        for w in where:
            sig[w:w + m] += t
        det = MatchedFilterDetector(t[None, :], capacity=4, normalize=False)
        scores, lags, values, counts = det(sig[None, :])
        assert scores.shape == (1, 1, n + m - 1)
        top2 = np.asarray(lags[0, 0])[np.argsort(-np.asarray(values[0, 0]))][:2]
        assert sorted(top2.tolist()) == where

    def test_template_bank_batched(self, rng):
        n, m, k, b = 512, 16, 3, 4
        bank = rng.normal(size=(k, m)).astype(np.float32)
        sigs = rng.normal(size=(b, n)).astype(np.float32)
        det = MatchedFilterDetector(bank, capacity=8)
        scores, lags, values, counts = det(sigs)
        assert scores.shape == (b, k, n + m - 1)
        assert lags.shape == (b, k, 8)
        assert counts.shape == (b, k)

    def test_scores_match_reference_correlation(self, rng):
        from veles.simd_tpu.reference import correlate as rc
        n, m = 128, 9
        sig = rng.normal(size=n).astype(np.float32)
        t = rng.normal(size=m).astype(np.float32)
        det = MatchedFilterDetector(t[None], capacity=4, normalize=False)
        scores, *_ = det(sig[None])
        want = rc.cross_correlate(sig, t)
        np.testing.assert_allclose(np.asarray(scores[0, 0]), want,
                                   rtol=1e-4, atol=1e-4)

    def test_validation(self):
        with pytest.raises(ValueError):
            MatchedFilterDetector(np.zeros((2, 2, 2), np.float32))
        with pytest.raises(ValueError):
            MatchedFilterDetector(np.zeros((1, 4), np.float32), capacity=0)


class TestWaveletDenoiser:
    def test_reduces_noise_mse(self, rng):
        n = 1024
        tt = np.linspace(0, 6 * np.pi, n)
        clean = np.sin(tt).astype(np.float32)
        noisy = clean + 0.3 * rng.normal(size=n).astype(np.float32)
        den = WaveletDenoiser("daubechies", 8, levels=4)
        out = np.asarray(den(noisy))
        assert out.shape == (n,)
        mse_before = np.mean((noisy - clean) ** 2)
        mse_after = np.mean((out - clean) ** 2)
        assert mse_after < 0.35 * mse_before

    def test_zero_noise_near_identity(self, rng):
        n = 512
        clean = np.sin(np.linspace(0, 4 * np.pi, n)).astype(np.float32)
        out = np.asarray(WaveletDenoiser(levels=3, threshold=0.0)(clean))
        np.testing.assert_allclose(out, clean, atol=1e-4)

    def test_batched_and_hard_mode(self, rng):
        x = rng.normal(size=(3, 256)).astype(np.float32)
        out = WaveletDenoiser(mode="hard", levels=2)(x)
        assert out.shape == (3, 256)

    def test_validation(self):
        with pytest.raises(ValueError):
            WaveletDenoiser(mode="medium")
        with pytest.raises(ValueError):
            WaveletDenoiser(levels=0)


class TestSignalPipeline:
    def test_jits_and_shapes(self, rng):
        import jax

        b, n, k, m = 4, 128, 8, 15
        sig = rng.normal(size=(b, n)).astype(np.float32)
        fir = rng.normal(size=m).astype(np.float32)
        w = (0.01 * rng.normal(size=(3 * n, k))).astype(np.float32)
        pipe = SignalPipeline()
        out = jax.jit(pipe)(sig, fir, w)
        assert out.shape == (b, k)
        assert np.isfinite(np.asarray(out)).all()

    def test_graft_entry_uses_pipeline(self):
        import __graft_entry__ as g
        import jax

        fn, args = g.entry()
        out = jax.jit(fn)(*args)
        assert out.shape == (8, 16)


class TestSpectralPeakAnalyzer:
    def test_recovers_tone_frequencies_subbin(self, rng):
        from veles.simd_tpu.models import SpectralPeakAnalyzer

        fs, n, batch = 8192.0, 4096, 3
        t = np.arange(n) / fs
        # non-bin-centered tones: sub-bin interpolation must recover them
        true_f = np.array([437.3, 1201.8, 2750.4])
        x = np.stack([
            np.sin(2 * np.pi * true_f[b] * t)
            + 0.05 * rng.normal(size=n)
            for b in range(batch)]).astype(np.float32)

        spa = SpectralPeakAnalyzer(nfft=512, capacity=2)
        power, freq_bins, logp, count = spa(x)
        assert power.shape == (batch, 257)
        hz = np.asarray(freq_bins)[:, 0] * fs / 512
        np.testing.assert_allclose(hz, true_f, atol=2.0)  # sub-bin (16 Hz)
        assert np.all(np.asarray(count) >= 1)

    def test_two_tones_ranked_by_power(self, rng):
        from veles.simd_tpu.models import SpectralPeakAnalyzer

        fs, n = 4096.0, 8192
        t = np.arange(n) / fs
        x = (np.sin(2 * np.pi * 300.0 * t)
             + 0.3 * np.sin(2 * np.pi * 900.0 * t)).astype(np.float32)
        spa = SpectralPeakAnalyzer(nfft=1024, capacity=2)
        _, freq_bins, _, count = spa(x)
        hz = np.asarray(freq_bins) * fs / 1024
        assert int(count) >= 2
        np.testing.assert_allclose(hz[:2], [300.0, 900.0], atol=1.0)

    def test_validation(self):
        from veles.simd_tpu.models import SpectralPeakAnalyzer

        with pytest.raises(ValueError, match="nfft"):
            SpectralPeakAnalyzer(nfft=4)
        spa = SpectralPeakAnalyzer(nfft=512)
        with pytest.raises(ValueError, match="signal length"):
            spa(np.zeros(100, np.float32))

    def test_irregular_hop_matches_regular_framing_path(self, rng):
        # both framing formulations must agree where they overlap; a
        # deterministic tone (not a noise argmax, which has no stable
        # dominant bin) makes that comparison seed-independent
        from veles.simd_tpu.models import SpectralPeakAnalyzer

        t = np.arange(2048, dtype=np.float32)
        x = (np.sin(2 * np.pi * 40.0 / 256.0 * t)
             + 0.01 * rng.normal(size=2048)).astype(np.float32)
        a = SpectralPeakAnalyzer(nfft=256, hop=128, capacity=2)   # fast path
        b = SpectralPeakAnalyzer(nfft=256, hop=127, capacity=2)   # loop path
        pa, fa, _, _ = a(x)
        pb, fb, _, _ = b(x)
        assert pa.shape == pb.shape
        # same dominant bin (40) despite slightly different Welch frames
        np.testing.assert_allclose(np.asarray(fa)[0], 40.0, atol=0.5)
        np.testing.assert_allclose(np.asarray(fb)[0], 40.0, atol=0.5)


class TestStreamingWaveletDenoiser:
    """Real-time shrinkage (models/streaming.py) vs the whole-signal
    decompose -> threshold -> recompose pipeline."""

    @pytest.mark.parametrize("order,levels", [(8, 3), (4, 2), (8, 1),
                                              (4, 4)])
    def test_matches_whole_signal(self, rng, order, levels):
        from veles.simd_tpu import ops
        from veles.simd_tpu.models import StreamingWaveletDenoiser

        n, chunk, th = 4096, 256, 0.8
        x = (np.sin(2 * np.pi * np.arange(n) / 64)
             + 0.3 * rng.standard_normal(n)).astype(np.float32)
        den = StreamingWaveletDenoiser("daubechies", order, levels, th)
        s = den.latency
        st = den.init()
        outs = []
        for i in range(0, n, chunk):
            st, y = den.step(st, x[i:i + chunk])
            outs.append(np.asarray(y))
        got = np.concatenate(outs)

        details, approx = ops.stationary_wavelet_decompose(
            x, levels, "daubechies", order)
        soft = lambda v: np.sign(v) * np.maximum(np.abs(v) - th, 0.0)
        details = [soft(np.asarray(d)).astype(np.float32) for d in details]
        want = np.asarray(ops.stationary_wavelet_recompose(
            details, approx, "daubechies", order))
        np.testing.assert_array_equal(got[2 * s:], want[s:n - s])

    def test_batched_and_scan(self, rng):
        import jax

        from veles.simd_tpu.models import StreamingWaveletDenoiser

        n, chunk = 2048, 256
        x = rng.standard_normal((3, n)).astype(np.float32)
        den = StreamingWaveletDenoiser(levels=2, thresholds=(0.5, 0.7))
        st = den.init(batch_shape=(3,))
        chunks = jnp.asarray(np.moveaxis(x.reshape(3, n // chunk, chunk),
                                         1, 0))
        _, ys = jax.lax.scan(lambda s, c: den.step(s, c), st, chunks)
        y = np.moveaxis(np.asarray(ys), 0, 1).reshape(3, n)

        st2 = den.init(batch_shape=(3,))
        outs = []
        for i in range(n // chunk):
            st2, yy = den.step(st2, x[:, i * chunk:(i + 1) * chunk])
            outs.append(np.asarray(yy))
        np.testing.assert_array_equal(y, np.concatenate(outs, axis=-1))

    def test_actually_denoises(self, rng):
        from veles.simd_tpu.models import StreamingWaveletDenoiser

        n = 8192
        t = np.arange(n, dtype=np.float32)
        clean = np.sin(2 * np.pi * t / 128).astype(np.float32)
        x = (clean + 0.4 * rng.standard_normal(n)).astype(np.float32)
        den = StreamingWaveletDenoiser(levels=3, thresholds=1.0)
        st = den.init()
        outs = []
        for i in range(0, n, 512):
            st, y = den.step(st, x[i:i + 512])
            outs.append(np.asarray(y))
        y = np.concatenate(outs)
        s = den.latency

        def snr(sig, ref):
            return 10 * np.log10((ref ** 2).sum() / ((sig - ref) ** 2).sum())

        before = snr(x[s:n - s], clean[s:n - s])
        after = snr(y[2 * s:], clean[s:n - s])
        assert after > before + 3.0, (before, after)

    def test_validation(self):
        from veles.simd_tpu.models import StreamingWaveletDenoiser

        with pytest.raises(ValueError, match="levels"):
            StreamingWaveletDenoiser(levels=0)
        with pytest.raises(ValueError, match="thresholds"):
            StreamingWaveletDenoiser(levels=3, thresholds=(1.0, 2.0))


class TestImageWaveletDenoiser:
    def test_snr_improves(self, rng):
        from veles.simd_tpu.models import ImageWaveletDenoiser

        h = w = 64
        yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
        clean = np.sin(2 * np.pi * yy / 32) * np.cos(2 * np.pi * xx / 16)
        noisy = clean + 0.3 * rng.normal(size=(h, w)).astype(np.float32)
        den = ImageWaveletDenoiser("daubechies", 8, levels=3)
        out = np.asarray(den(noisy))
        err_in = float(np.mean((noisy - clean) ** 2))
        err_out = float(np.mean((out - clean) ** 2))
        assert out.shape == (h, w)
        assert err_out < err_in / 2, (err_in, err_out)

    def test_batched_and_fixed_threshold(self, rng):
        from veles.simd_tpu.models import ImageWaveletDenoiser

        imgs = rng.normal(size=(3, 32, 32)).astype(np.float32)
        den = ImageWaveletDenoiser(levels=2, mode="hard", threshold=10.0)
        out = np.asarray(den(imgs))
        assert out.shape == imgs.shape
        # threshold 10 kills every detail band of unit-variance noise:
        # the output is the ll-band-only reconstruction (a lowpass);
        # energy strictly drops
        assert float(np.sum(out ** 2)) < float(np.sum(imgs ** 2))

    def test_contracts(self):
        from veles.simd_tpu.models import ImageWaveletDenoiser

        with pytest.raises(ValueError):
            ImageWaveletDenoiser(mode="bogus")
        with pytest.raises(ValueError):
            ImageWaveletDenoiser(levels=0)


class TestTransientScalogramDetector:
    def test_finds_injected_bursts(self, rng):
        """Gausspulse bursts at known times in noise: every burst
        recovered at roughly its own duration scale, no extras."""
        from veles.simd_tpu import ops as vops
        from veles.simd_tpu.models import TransientScalogramDetector

        n = 8192
        t = np.arange(n, dtype=np.float32) / 2000.0
        centers = [1000, 3000, 5500, 7200]
        x = 0.2 * rng.normal(size=n).astype(np.float32)
        for c in centers:
            burst = np.asarray(vops.gausspulse(t - t[c], fc=60.0,
                                               bw=0.4))
            x += 1.2 * burst
        det = TransientScalogramDetector(capacity=16, distance=400.0,
                                         prominence=4.0)
        pos, val, scales, count = det(x)
        found = sorted(int(p) for p in np.asarray(pos)[:int(count)])
        assert len(found) == len(centers), (found, centers)
        for c in centers:
            assert any(abs(f - c) < 100 for f in found), (c, found)
        assert np.all(np.asarray(scales)[:int(count)] > 0)

    def test_jits_and_vmaps(self, rng):
        import jax
        from veles.simd_tpu.models import TransientScalogramDetector

        det = TransientScalogramDetector(capacity=8, distance=50.0)
        x = rng.normal(size=(3, 1024)).astype(np.float32)
        pos, val, scales, count = jax.vmap(det)(x)
        assert pos.shape == (3, 8) and count.shape == (3,)
