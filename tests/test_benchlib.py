"""Timing-harness unit tests (the benchmark.inc analogue's plumbing).

Rates themselves are only meaningful on hardware; these cover the chain
construction contracts on CPU with tiny shapes.
"""

import math
import os

import pytest

import jax.numpy as jnp

from veles.simd_tpu.utils.benchlib import chain_time, chain_times, make_chain


def test_make_chain_applies_step_iters_times():
    chain = make_chain(lambda c: c + 1.0, 5)
    out = float(chain(jnp.zeros(3, jnp.float32)))
    assert out == pytest.approx(15.0)  # 3 leaves x 5 increments


def test_pytree_carry():
    # the null chain must compile for dict carries (tree_map, not c * s)
    carry = {"a": jnp.ones(4, jnp.float32), "b": jnp.zeros(2, jnp.float32)}
    times = chain_times(
        {"_": lambda c: {"a": c["a"] * 1.0, "b": c["b"] + c["a"][:2]}},
        carry, iters=4, reps=1, on_floor="nan")
    dt = times["_"]
    assert math.isfinite(dt) or math.isnan(dt)  # tiny op may sit at floor


def test_non_finite_checksum_raises():
    # failed-leg isolation (r3): chain_stats records the reason per leg;
    # strict (on_floor="raise") chain_time callers still get the loud
    # failure, with the reason in the message
    with pytest.raises(RuntimeError, match="non-finite"):
        chain_time(lambda c: c * jnp.float32(2.0),
                   jnp.full(4, 1e30, jnp.float32), iters=64, reps=1)
    from veles.simd_tpu.utils.benchlib import chain_stats
    sts = chain_stats({"_": lambda c: c * jnp.float32(2.0)},
                      jnp.full(4, 1e30, jnp.float32), iters=64, reps=1,
                      on_floor="nan")
    assert "non-finite" in sts["_"]["error"]


@pytest.mark.skipif(os.environ.get("VELES_TEST_TPU") == "1",
                    reason="RTT-floor detection is inherently noisy on the "
                           "live tunnel; the mechanics are platform-free "
                           "and validated on CPU")
def test_on_floor_nan_keeps_other_configs():
    carry = jnp.ones(8, jnp.float32)
    steps = {
        "free": lambda c: c,  # guaranteed at the RTT floor
        "work": lambda c: jnp.fft.rfft(jnp.tile(c, 4096)).real[:8] * 0 + c,
    }
    times = chain_times(steps, carry, iters=32, reps=1, on_floor="nan")
    assert math.isnan(times["free"])
    assert math.isfinite(times["work"]) and times["work"] > 0


def test_on_floor_raise_default(monkeypatch):
    # Deterministic floor hit: fake the clock so every chain measures the
    # exact same elapsed time as the null chain (real timings of a no-op
    # chain are scheduler noise and made this test flaky under load).
    from veles.simd_tpu.utils import benchlib

    ticks = iter(range(10000))

    class _FakeTime:
        @staticmethod
        def perf_counter():
            return float(next(ticks))

    monkeypatch.setattr(benchlib, "time", _FakeTime)
    with pytest.raises(RuntimeError, match="floor"):
        chain_times({"free": lambda c: c}, jnp.ones(8, jnp.float32),
                    iters=32, reps=1)


def test_feed_io_config_smoke():
    # the loader-throughput config must produce a finite positive rate
    # at tiny scale (bench_extra configs are otherwise only run on TPU)
    from veles.simd_tpu.utils.bench_extra import bench_feed_io

    out = bench_feed_io(scale=1 / 64)
    assert out["unit"] == "MSamples/s"
    assert math.isfinite(out["value"]) and out["value"] > 0


def test_chain_stats_keys_and_ordering():
    """chain_stats returns corrected/raw/floor per config with
    raw >= corrected (the raw wall-clock is the unimpeachable bound)."""
    from veles.simd_tpu.utils.benchlib import chain_stats

    carry = jnp.ones((64, 64), jnp.float32)
    sts = chain_stats({"mm": lambda c: c @ c * 1e-3}, carry,
                      iters=16, reps=2, on_floor="nan")
    st = sts["mm"]
    assert set(st) == {"sec", "raw_sec", "floor_sec", "attempt_sec"}
    assert st["raw_sec"] > 0 and st["floor_sec"] > 0
    assert len(st["attempt_sec"]) == 1  # attempts defaults to 1
    if math.isfinite(st["sec"]):
        assert st["raw_sec"] >= st["sec"]


def test_bench_collect_secondary_shape(monkeypatch):
    """collect_secondary returns {metric: record}; a raising config
    contributes an error record without killing the rest."""
    from veles.simd_tpu.utils import bench_extra

    def boom(scale=1):
        raise RuntimeError("nope")

    def tiny(scale=1):
        return {"metric": "tiny", "value": 1.0, "unit": "x",
                "vs_baseline": None}

    monkeypatch.setattr(bench_extra, "CONFIGS", (tiny, boom))
    out = bench_extra.collect_secondary(scale=1)
    assert out["tiny"]["value"] == 1.0
    assert "error" in out["boom"]


def test_per_leg_iters():
    """r4: iters may be {name: iters} — each leg times a chain of its
    own length and corrects against a matching-length null floor (the
    mxu convolve leg needs 16x the chain of its slow siblings)."""
    import jax.numpy as jnp

    from veles.simd_tpu.utils.benchlib import chain_stats

    carry = jnp.ones((4, 256), jnp.float32)
    sts = chain_stats({"fast": lambda c: c * jnp.float32(1.0000001),
                       "slow": lambda c: c @ jnp.ones((256, 256)) * 0 + c},
                      carry, iters={"fast": 64, "slow": 8},
                      reps=1, on_floor="nan", null_carry=carry[:1, :8])
    for leg in ("fast", "slow"):
        assert sts[leg]["raw_sec"] > 0
    # raw_sec is per STEP: the fast leg's 64-step chain must not be
    # divided by the slow leg's 8 (a shared-iters bug would inflate it)
    assert sts["fast"]["raw_sec"] < sts["slow"]["raw_sec"] * 8
