"""Polyphase resampling suite (framework extension; no reference-C
analogue — the oracle is the float64 zero-stuff definition, cross-checked
against scipy.signal.upfirdn where available)."""

import numpy as np
import pytest

from veles.simd_tpu import ops
from veles.simd_tpu.reference import resample as ref_resample


class TestUpfirdn:
    @pytest.mark.parametrize("up,down", [(1, 1), (2, 1), (1, 2), (3, 2),
                                         (2, 3), (4, 4), (5, 3), (7, 4)])
    @pytest.mark.parametrize("n,m", [(64, 9), (130, 31), (257, 16)])
    def test_differential(self, rng, up, down, n, m):
        x = rng.normal(size=n).astype(np.float32)
        h = rng.normal(size=m).astype(np.float32)
        want = ref_resample.upfirdn(x, h, up, down)
        got = np.asarray(ops.upfirdn(x, h, up, down))
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-3)

    def test_matches_scipy(self, rng):
        scipy_signal = pytest.importorskip("scipy.signal")
        x = rng.normal(size=100).astype(np.float64)
        h = rng.normal(size=21).astype(np.float64)
        want = scipy_signal.upfirdn(h, x, up=3, down=2)
        got = ref_resample.upfirdn(x, h, 3, 2)
        np.testing.assert_allclose(got, want, atol=1e-12)

    def test_identity_is_convolution(self, rng):
        x = rng.normal(size=100).astype(np.float32)
        h = rng.normal(size=15).astype(np.float32)
        got = np.asarray(ops.upfirdn(x, h, 1, 1))
        want = np.asarray(ops.convolve(x, h, algorithm="direct"))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)

    def test_batched(self, rng):
        batch = rng.normal(size=(3, 4, 96)).astype(np.float32)
        h = rng.normal(size=13).astype(np.float32)
        got = np.asarray(ops.upfirdn(batch, h, 3, 2))
        want = ref_resample.upfirdn(batch, h, 3, 2)
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-3)

    def test_bad_factors(self):
        with pytest.raises(ValueError):
            ops.upfirdn(np.zeros(8, np.float32), np.ones(3, np.float32),
                        up=0)


class TestResamplePoly:
    @pytest.mark.parametrize("up,down", [(2, 1), (1, 2), (3, 2), (2, 3),
                                         (160, 147)])
    def test_length_and_oracle(self, rng, up, down):
        n = 441
        x = rng.normal(size=n).astype(np.float32)
        h = ops.resample_filter(up, down, taps_per_phase=4)
        want = ref_resample.resample_poly(x, up, down, h)
        got = np.asarray(ops.resample_poly(x, up, down, h))
        assert got.shape[-1] == -(-n * up // down)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-3)

    def test_sine_preserved(self, rng):
        # a tone well below both Nyquists survives 3/2 resampling with
        # the same amplitude and the exact t*down/up time alignment
        n, up, down = 2048, 3, 2
        t = np.arange(n, dtype=np.float64)
        x = np.sin(2 * np.pi * 0.01 * t).astype(np.float32)
        y = np.asarray(ops.resample_poly(x, up, down))
        t_out = np.arange(y.shape[-1], dtype=np.float64) * down / up
        want = np.sin(2 * np.pi * 0.01 * t_out)
        # ignore filter-length edge transients on both ends
        edge = 64
        np.testing.assert_allclose(y[edge:-edge], want[edge:-edge],
                                   atol=5e-3)

    def test_default_filter_dc_gain(self):
        # unity DC gain after upsampling: a constant resamples to itself
        x = np.ones(512, np.float32)
        y = np.asarray(ops.resample_poly(x, 2, 1))
        mid = y[100:-100]
        np.testing.assert_allclose(mid, np.ones_like(mid), atol=1e-3)

    def test_batched(self, rng):
        batch = rng.normal(size=(5, 200)).astype(np.float32)
        h = ops.resample_filter(2, 3, taps_per_phase=4)
        got = np.asarray(ops.resample_poly(batch, 2, 3, h))
        want = ref_resample.resample_poly(batch, 2, 3, h)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-3)


class TestResampleStream:
    """Streaming upfirdn: chunk-concat equals the whole-signal causal
    body exactly (the framework's streaming exactness contract)."""

    @pytest.mark.parametrize("up,down,chunk", [(1, 1, 64), (2, 1, 64),
                                               (1, 2, 64), (3, 2, 64),
                                               (2, 3, 96), (5, 4, 80)])
    def test_concat_matches_whole(self, rng, up, down, chunk):
        n = chunk * 6
        x = rng.normal(size=n).astype(np.float32)
        h = rng.normal(size=23).astype(np.float32)
        st = ops.resample_stream_init(h, up, down)
        outs = []
        for i in range(0, n, chunk):
            st, y = ops.resample_stream_step(st, x[i:i + chunk], h,
                                             up=up, down=down)
            outs.append(np.asarray(y))
        got = np.concatenate(outs)
        want = np.asarray(ops.upfirdn(x, h, up, down))[:n * up // down]
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)

    def test_batched(self, rng):
        x = rng.normal(size=(3, 128)).astype(np.float32)
        h = rng.normal(size=11).astype(np.float32)
        st = ops.resample_stream_init(h, 3, 2, batch_shape=(3,))
        st, y1 = ops.resample_stream_step(st, x[:, :64], h, up=3, down=2)
        st, y2 = ops.resample_stream_step(st, x[:, 64:], h, up=3, down=2)
        got = np.concatenate([np.asarray(y1), np.asarray(y2)], axis=-1)
        want = np.asarray(ops.upfirdn(x, h, 3, 2))[..., :128 * 3 // 2]
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)

    def test_chunk_constraint(self):
        h = np.ones(5, np.float32)
        st = ops.resample_stream_init(h, 2, 3)
        with pytest.raises(ValueError, match="divisible"):
            ops.resample_stream_step(st, np.zeros(64, np.float32), h,
                                     up=2, down=3)


class TestResampleFuzz:
    """Random (up, down, n, m) vs the float64 oracle."""

    @pytest.mark.parametrize("seed", range(6))
    def test_random_factors_agree(self, seed):
        g = np.random.default_rng(5000 + seed)
        up = int(g.integers(1, 9))
        down = int(g.integers(1, 9))
        n = int(g.integers(8, 1500))
        m = int(g.integers(1, 80))
        x = g.normal(size=n).astype(np.float32)
        h = (g.normal(size=m) / max(m, 1)).astype(np.float32)
        want = ref_resample.upfirdn(x, h, up, down)
        got = np.asarray(ops.upfirdn(x, h, up, down))
        assert got.shape == want.shape, (up, down, n, m)
        scale = np.abs(want).max() + 1.0
        np.testing.assert_allclose(
            got / scale, want / scale, atol=5e-5,
            err_msg=f"seed={seed} up={up} down={down} n={n} m={m}")


def test_identity_ratio_returns_input(rng):
    x = rng.normal(size=100).astype(np.float32)
    np.testing.assert_array_equal(np.asarray(ops.resample_poly(x, 1, 1)), x)
    # gcd reduction: 3/3 is the identity too
    np.testing.assert_array_equal(np.asarray(ops.resample_poly(x, 3, 3)), x)
    # scipy's up==down short-circuit precedes window handling: an
    # explicitly supplied h must not break the identity (ADVICE r2)
    h = rng.normal(size=31).astype(np.float32)
    np.testing.assert_array_equal(
        np.asarray(ops.resample_poly(x, 2, 2, h=h)), x)
    with pytest.raises(ValueError, match="identity"):
        ops.resample_filter(1, 1)


def test_stream_step_rejects_bad_factors():
    h = np.ones(5, np.float32)
    st = ops.resample_stream_init(h, 2, 1)
    with pytest.raises(ValueError, match=">= 1"):
        ops.resample_stream_step(st, np.zeros(8, np.float32), h,
                                 up=2, down=0)


class TestFourierResample:
    """ops.resample (FFT method) vs scipy.signal.resample."""

    @pytest.mark.parametrize("n,num", [(100, 50), (100, 37), (100, 200),
                                       (128, 128), (99, 66), (64, 129)])
    def test_differential(self, rng, n, num):
        x = rng.normal(size=n).astype(np.float32)
        want = ops.resample(x, num, impl="reference")
        got = np.asarray(ops.resample(x, num))
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_batched(self, rng):
        x = rng.normal(size=(2, 3, 80)).astype(np.float32)
        want = ops.resample(x, 120, impl="reference")
        got = np.asarray(ops.resample(x, 120))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_tone_survives(self):
        """A pure in-band tone resamples to the same tone at the new
        rate (the periodic-extension method's exactness case)."""
        n, num = 256, 384
        t = np.arange(n)
        x = np.sin(2 * np.pi * 10 * t / n).astype(np.float32)
        got = np.asarray(ops.resample(x, num))
        want = np.sin(2 * np.pi * 10 * np.arange(num) / num)
        np.testing.assert_allclose(got, want, atol=1e-4)

    def test_contracts(self, rng):
        with pytest.raises(ValueError):
            ops.resample(np.zeros(8, np.float32), 0)
