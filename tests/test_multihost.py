"""Multi-host layer tests — single-process behavior only (no pod here);
the hybrid mesh must collapse transparently so specs written against it
run unchanged on real DCN topologies."""

import pytest

from veles.simd_tpu.parallel import multihost


def test_process_info_single_process():
    assert multihost.process_info() == (0, 1)


def test_hybrid_mesh_collapses_single_host():
    mesh = multihost.hybrid_mesh({"data": 2}, {"seq": 4})
    assert mesh.axis_names == ("data", "seq")
    assert mesh.shape == {"data": 2, "seq": 4}


def test_hybrid_mesh_axis_order_is_dcn_outer():
    mesh = multihost.hybrid_mesh({"dp": 1}, {"seq": 8})
    assert mesh.axis_names == ("dp", "seq")
    assert mesh.devices.shape == (1, 8)


def test_overlapping_axis_names_rejected():
    with pytest.raises(ValueError, match="both"):
        multihost.hybrid_mesh({"seq": 2}, {"seq": 4})


def test_initialize_noop_without_coordinator():
    multihost.initialize()  # must not raise in single-process mode


def test_initialize_raises_with_bad_explicit_coordinator():
    with pytest.raises(Exception):
        multihost.initialize("256.0.0.1:1", num_processes=2, process_id=0,
                             initialization_timeout=1)
