"""Multi-host layer tests — single-process behavior only (no pod here);
the hybrid mesh must collapse transparently so specs written against it
run unchanged on real DCN topologies."""

import pytest

from veles.simd_tpu.parallel import multihost


def test_process_info_single_process():
    assert multihost.process_info() == (0, 1)


def test_hybrid_mesh_collapses_single_host():
    mesh = multihost.hybrid_mesh({"data": 2}, {"seq": 4})
    assert mesh.axis_names == ("data", "seq")
    assert mesh.shape == {"data": 2, "seq": 4}


def test_hybrid_mesh_axis_order_is_dcn_outer():
    mesh = multihost.hybrid_mesh({"dp": 1}, {"seq": 8})
    assert mesh.axis_names == ("dp", "seq")
    assert mesh.devices.shape == (1, 8)


def test_overlapping_axis_names_rejected():
    with pytest.raises(ValueError, match="both"):
        multihost.hybrid_mesh({"seq": 2}, {"seq": 4})


def test_hybrid_mesh_multiprocess_padded_shapes(monkeypatch):
    """The pod path must hand create_hybrid_device_mesh SAME-RANK ici/dcn
    shapes whose elementwise product is (dcn..., ici...) — jax np.block-
    assembles the product, it does not concatenate dims."""
    import numpy as np
    import jax

    captured = {}

    def fake_chdm(mesh_shape, dcn_mesh_shape, devices=None):
        assert len(mesh_shape) == len(dcn_mesh_shape)
        shape = tuple(np.multiply(mesh_shape, dcn_mesh_shape))
        return np.array(jax.devices()[: int(np.prod(shape))],
                        dtype=object).reshape(shape)

    from jax.experimental import mesh_utils
    monkeypatch.setattr(mesh_utils, "create_hybrid_device_mesh", fake_chdm)
    monkeypatch.setattr(jax, "process_count", lambda: 2)

    mesh = multihost.hybrid_mesh({"data": 2}, {"seq": 4})
    assert mesh.axis_names == ("data", "seq")
    assert mesh.devices.shape == (2, 4)
    captured  # silence lint


def test_initialize_noop_without_coordinator():
    multihost.initialize()  # must not raise in single-process mode


def test_initialize_raises_with_bad_explicit_coordinator():
    with pytest.raises(Exception):
        multihost.initialize("256.0.0.1:1", num_processes=2, process_id=0,
                             initialization_timeout=1)
