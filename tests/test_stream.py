"""Streaming ops (ops/stream.py): chunked == whole-signal differential.

The contract under test is the module's oracle: concatenated step
outputs must equal the whole-signal op on the concatenated input — the
streaming rebirth of the reference's carried overlap-save block loop
(src/convolve.c:181-228)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veles.simd_tpu import ops


def _chunks(x, size):
    return [x[..., i:i + size] for i in range(0, x.shape[-1], size)]


@pytest.mark.parametrize("h_len", [1, 4, 31, 127])
@pytest.mark.parametrize("chunk", [64, 100, 256])
def test_fir_stream_matches_whole(rng, h_len, chunk):
    n = 1024
    x = rng.standard_normal(n, dtype=np.float32)
    h = rng.standard_normal(h_len, dtype=np.float32)
    want = np.asarray(ops.causal_fir(x, h))

    state = ops.fir_stream_init(h)
    outs = []
    for c in _chunks(x, chunk):
        state, y = ops.fir_stream_step(state, c, h)
        outs.append(np.asarray(y))
    got = np.concatenate(outs)
    np.testing.assert_array_equal(got, want)


def test_fir_stream_batched(rng):
    x = rng.standard_normal((3, 512), dtype=np.float32)
    h = rng.standard_normal(17, dtype=np.float32)
    want = np.asarray(ops.causal_fir(x, h))
    state = ops.fir_stream_init(h, batch_shape=(3,))
    outs = []
    for c in _chunks(x, 128):
        state, y = ops.fir_stream_step(state, c, h)
        outs.append(np.asarray(y))
    np.testing.assert_array_equal(np.concatenate(outs, axis=-1), want)


def test_minmax_stream(rng):
    x = rng.standard_normal((2, 777), dtype=np.float32)
    state = ops.minmax_stream_init(batch_shape=(2,))
    for c in _chunks(x, 100):
        state, (vmin, vmax) = ops.minmax_stream_step(state, c)
    np.testing.assert_array_equal(np.asarray(vmin), x.min(axis=-1))
    np.testing.assert_array_equal(np.asarray(vmax), x.max(axis=-1))
    # the running result feeds the rescale second pass exactly as
    # minmax feeds normalize (normalize.c:435-441), per row here
    from veles.simd_tpu.ops.normalize import rescale_minmax
    # stats derive from x itself -> clip=True per normalize.py:41-45
    # (TPU reciprocal rounding can land 1 ulp outside the interval)
    got = np.asarray(rescale_minmax(x, vmin[..., None], vmax[..., None],
                                    clip=True))
    assert got.min() >= -1.0 and got.max() <= 1.0
    assert got.shape == x.shape


def _stream_peaks(x, chunk, capacity_per_chunk=None):
    state = ops.peaks_stream_init()
    all_pos, all_val = [], []
    for c in _chunks(x, chunk):
        state, (pos, val, count) = ops.peaks_stream_step(
            state, c, capacity=capacity_per_chunk or c.shape[-1])
        k = int(count)
        all_pos.extend(np.asarray(pos)[:k].tolist())
        all_val.extend(np.asarray(val)[:k].tolist())
    return np.array(all_pos), np.array(all_val, np.float32)


@pytest.mark.parametrize("chunk", [64, 100, 128])
def test_peaks_stream_matches_whole(rng, chunk):
    n = 512
    x = rng.standard_normal(n, dtype=np.float32)
    pos, val, count = ops.detect_peaks_fixed(x, capacity=n - 2)
    k = int(count)
    want_pos = np.asarray(pos)[:k]
    want_val = np.asarray(val)[:k]

    got_pos, got_val = _stream_peaks(x, chunk)
    np.testing.assert_array_equal(got_pos, want_pos)
    np.testing.assert_array_equal(got_val, want_val)


def test_peaks_stream_boundary_peak(rng):
    """A peak exactly at a chunk boundary (last sample of chunk k) must
    be reported once, by the step that makes it decidable."""
    x = np.zeros(128, np.float32)
    x[63] = 1.0     # last sample of the first 64-chunk
    x[64] = -1.0    # first sample of the second
    got_pos, got_val = _stream_peaks(x, 64)
    pos, val, count = ops.detect_peaks_fixed(x, capacity=126)
    np.testing.assert_array_equal(got_pos, np.asarray(pos)[:int(count)])
    np.testing.assert_array_equal(got_val, np.asarray(val)[:int(count)])
    assert 63 in got_pos.tolist() and 64 in got_pos.tolist()


def test_peaks_stream_truncation():
    """Pin the per-STEP capacity semantics: each chunk keeps its first
    ``capacity`` decidable peaks, so the stream union can retain later
    peaks a capacity-limited whole-signal call would drop (documented in
    peaks_stream_step; ADVICE round-1 item)."""
    # alternating signal: every interior odd index is a max, evens are
    # mins -> with EXTREMUM_TYPE_BOTH every interior point is a peak
    x = np.tile(np.array([1.0, -1.0], np.float32), 64)  # n = 128
    chunk, cap = 32, 4
    got_pos, _ = _stream_peaks(x, chunk, capacity_per_chunk=cap)
    # 4 chunks x 4 peaks: the FIRST 4 decidable per chunk
    assert len(got_pos) == 4 * cap
    # each chunk k decides global positions [32k-1, 32(k+1)-2]; its kept
    # peaks are the first cap of those
    want = []
    for k in range(4):
        lo = max(1, 32 * k - 1)
        want.extend(range(lo, lo + cap))
    np.testing.assert_array_equal(np.sort(got_pos), np.sort(want))
    # the whole-signal call at the same capacity keeps only the global
    # first cap -> strictly fewer, earlier positions
    pos_w, _, cnt_w = ops.detect_peaks_fixed(x, capacity=cap)
    np.testing.assert_array_equal(np.asarray(pos_w)[:int(cnt_w)],
                                  got_pos[:cap])
    assert int(cnt_w) == cap


def test_peaks_stream_first_sample_not_tested():
    """Global index 0 is never a peak (whole-signal interior starts at 1,
    detect_peaks.c:67) even when the stream opens with a local max."""
    x = np.r_[np.float32(5.0), np.zeros(63, np.float32)]
    got_pos, _ = _stream_peaks(x, 32)
    assert 0 not in got_pos.tolist()


def test_stream_scan_fir(rng):
    n, chunk = 1024, 128
    x = rng.standard_normal(n, dtype=np.float32)
    h = rng.standard_normal(15, dtype=np.float32)
    chunks = jnp.asarray(x.reshape(n // chunk, chunk))
    state = ops.fir_stream_init(h)
    final, ys = ops.stream_scan(ops.fir_stream_step, state, chunks, h)
    got = np.asarray(ys).reshape(-1)
    np.testing.assert_array_equal(got, np.asarray(ops.causal_fir(x, h)))
    assert final.tail.shape == (14,)


def test_stream_scan_peaks(rng):
    n, chunk = 512, 64
    x = rng.standard_normal(n, dtype=np.float32)
    chunks = jnp.asarray(x.reshape(n // chunk, chunk))
    state = ops.peaks_stream_init()
    _, (pos, val, count) = ops.stream_scan(
        ops.peaks_stream_step, state, chunks, capacity=chunk)
    got_pos = []
    for i in range(n // chunk):
        got_pos.extend(np.asarray(pos[i])[:int(count[i])].tolist())
    wpos, _, wcount = ops.detect_peaks_fixed(x, capacity=n - 2)
    np.testing.assert_array_equal(np.array(got_pos),
                                  np.asarray(wpos)[:int(wcount)])


@pytest.mark.parametrize("order,level", [(2, 1), (8, 1), (4, 2), (6, 3),
                                         (12, 2)])
@pytest.mark.parametrize("chunk", [128, 200])
def test_swt_stream_matches_whole_delayed(rng, order, level, chunk):
    """Streamed à-trous bank == whole-signal SWT delayed by D, exactly,
    for every sample whose window never crosses the signal end (the
    extension region a stream cannot see)."""
    n = 1024
    x = rng.standard_normal(n, dtype=np.float32)
    d = ops.swt_stream_delay(order, level)
    state = ops.swt_stream_init(order, level)
    his, los = [], []
    for c in _chunks(x, chunk):
        state, (hi, lo) = ops.swt_stream_step(
            state, c, "daubechies", order, level)
        his.append(np.asarray(hi))
        los.append(np.asarray(lo))
    got_hi = np.concatenate(his)[d:]
    got_lo = np.concatenate(los)[d:]
    want_hi, want_lo = ops.stationary_wavelet_apply(
        x, "daubechies", order, level=level)
    np.testing.assert_array_equal(got_hi, np.asarray(want_hi)[:n - d])
    np.testing.assert_array_equal(got_lo, np.asarray(want_lo)[:n - d])


def test_swt_stream_cascade_two_levels(rng):
    """Feeding level-1 lo into a level-2 stream reproduces the
    whole-signal cascade with the delays summed — the shift-invariance
    of the undecimated transform, streamed."""
    n, chunk, order = 1024, 128, 4
    x = rng.standard_normal(n, dtype=np.float32)
    d1 = ops.swt_stream_delay(order, 1)
    d2 = ops.swt_stream_delay(order, 2)
    s1 = ops.swt_stream_init(order, 1)
    s2 = ops.swt_stream_init(order, 2)
    hi2s = []
    for c in _chunks(x, chunk):
        s1, (_, lo1) = ops.swt_stream_step(s1, c, "daubechies", order, 1)
        s2, (hi2, _) = ops.swt_stream_step(s2, lo1, "daubechies", order, 2)
        hi2s.append(np.asarray(hi2))
    got = np.concatenate(hi2s)[d1 + d2:]

    _, wlo1 = ops.stationary_wavelet_apply(x, "daubechies", order, level=1)
    whi2, _ = ops.stationary_wavelet_apply(
        np.asarray(wlo1), "daubechies", order, level=2)
    np.testing.assert_array_equal(got, np.asarray(whi2)[:n - d1 - d2])


def test_swt_stream_scan(rng):
    n, chunk, order = 2048, 256, 8
    x = rng.standard_normal(n, dtype=np.float32)
    chunks = jnp.asarray(x.reshape(n // chunk, chunk))
    state = ops.swt_stream_init(order)
    _, (his, los) = ops.stream_scan(ops.swt_stream_step, state, chunks,
                                    "daubechies", order, 1)
    d = ops.swt_stream_delay(order)
    want_hi, _ = ops.stationary_wavelet_apply(x, "daubechies", order)
    np.testing.assert_array_equal(np.asarray(his).reshape(-1)[d:],
                                  np.asarray(want_hi)[:n - d])


def test_fir_stream_state_is_checkpointable(tmp_path, rng):
    """Streaming state is a plain pytree — utils/checkpoint roundtrips it
    (the resume story the reference lacks, SURVEY §5)."""
    from veles.simd_tpu.utils import checkpoint

    x = rng.standard_normal(256, dtype=np.float32)
    h = rng.standard_normal(9, dtype=np.float32)
    state = ops.fir_stream_init(h)
    state, _ = ops.fir_stream_step(state, x[:128], h)
    checkpoint.save(str(tmp_path / "st"), {"tail": state.tail})
    restored = checkpoint.restore(str(tmp_path / "st"))
    resumed = ops.FirStreamState(jnp.asarray(restored["tail"]))
    _, y2 = ops.fir_stream_step(resumed, x[128:], h)
    want = np.asarray(ops.causal_fir(x, h))[128:]
    np.testing.assert_array_equal(np.asarray(y2), want)


@pytest.mark.native_complex  # fetches complex spectra to host
@pytest.mark.parametrize("nfft,hop,chunk", [(256, 64, 256), (256, 128, 512),
                                            (128, 32, 96), (64, 64, 128)])
def test_stft_stream_matches_whole(rng, nfft, hop, chunk):
    """Concatenated streamed frames (past warm-up) == ops.stft exactly."""
    n = 2048
    x = rng.standard_normal(n, dtype=np.float32)
    warm = ops.stft_stream_warmup(nfft, hop)
    state = ops.stft_stream_init(nfft, hop)
    specs = []
    for c in _chunks(x, chunk):
        state, s = ops.stft_stream_step(state, c, nfft=nfft, hop=hop)
        specs.append(np.asarray(s))
    got = np.concatenate(specs, axis=-2)[warm:]
    want = np.asarray(ops.stft(x, nfft=nfft, hop=hop))
    np.testing.assert_array_equal(got, want[:got.shape[-2]])
    assert got.shape == want.shape  # frame budgets agree exactly


def test_stft_stream_magnitude(rng):
    """Host-transfer-safe twin (per-frame power is real) + batch."""
    nfft, hop, chunk = 128, 32, 256
    x = rng.standard_normal((3, 1024), dtype=np.float32)
    warm = ops.stft_stream_warmup(nfft, hop)
    state = ops.stft_stream_init(nfft, hop, batch_shape=(3,))
    mags = []
    for c in _chunks(x, chunk):
        state, s = ops.stft_stream_step(state, c, nfft=nfft, hop=hop)
        mags.append(np.asarray(jnp.abs(s) ** 2))
    got = np.concatenate(mags, axis=-2)[:, warm:]
    want = np.asarray(ops.spectrogram(x, nfft=nfft, hop=hop))
    np.testing.assert_allclose(got, want[:, :got.shape[-2]],
                               rtol=1e-4, atol=1e-5)


def test_stft_stream_validation():
    with pytest.raises(ValueError, match="nfft % hop"):
        ops.stft_stream_init(100, 33)
    st = ops.stft_stream_init(128, 32)
    with pytest.raises(ValueError, match="multiple"):
        ops.stft_stream_step(st, np.zeros(100, np.float32), nfft=128,
                             hop=32)


@pytest.mark.parametrize("order,level", [(2, 1), (8, 1), (4, 2), (6, 3),
                                         (12, 2)])
def test_swt_stream_roundtrip(rng, order, level):
    """Streamed analysis -> streamed synthesis == input delayed by D
    (the analysis delay alone; synthesis is causal), past a 2D warm-up."""
    n, chunk = 2048, 256
    x = rng.standard_normal(n, dtype=np.float32)
    d = ops.swt_stream_delay(order, level)
    sa = ops.swt_stream_init(order, level)
    sr = ops.swt_stream_reconstruct_init(order, level)
    outs = []
    for c in _chunks(x, chunk):
        sa, (hi, lo) = ops.swt_stream_step(sa, c, "daubechies", order,
                                           level)
        sr, y = ops.swt_stream_reconstruct_step(sr, hi, lo, "daubechies",
                                                order, level)
        outs.append(np.asarray(y))
    y = np.concatenate(outs)
    np.testing.assert_allclose(y[2 * d:], x[d:n - d], atol=2e-6)


def test_swt_stream_reconstruct_matches_whole(rng):
    """Fed TRUE whole-signal bands, the synthesis stream equals the
    whole-signal reconstruction exactly past its span warm-up."""
    n, order = 1024, 8
    x = rng.standard_normal(n, dtype=np.float32)
    hi, lo = ops.stationary_wavelet_apply(x, "daubechies", order)
    want = np.asarray(ops.stationary_wavelet_reconstruct(
        hi, lo, "daubechies", order))
    d = ops.swt_stream_delay(order, 1)
    sr = ops.swt_stream_reconstruct_init(order, 1)
    outs = []
    hi, lo = np.asarray(hi), np.asarray(lo)
    for i in range(0, n, 128):
        sr, y = ops.swt_stream_reconstruct_step(
            sr, hi[i:i + 128], lo[i:i + 128], "daubechies", order, 1)
        outs.append(np.asarray(y))
    y = np.concatenate(outs)
    np.testing.assert_array_equal(y[d:], want[d:])


def test_swt_stream_denoise_realtime(rng):
    """The composition the inverse stream exists for: real-time wavelet
    shrinkage (analysis -> soft-threshold hi -> synthesis) equals the
    whole-signal shrinkage, delayed by D."""
    n, chunk, order, thresh = 2048, 256, 8, 0.8
    t = np.arange(n, dtype=np.float32)
    x = (np.sin(2 * np.pi * t / 64)
         + 0.3 * rng.standard_normal(n)).astype(np.float32)

    def soft(v):
        return np.sign(v) * np.maximum(np.abs(v) - thresh, 0.0)

    hi_w, lo_w = ops.stationary_wavelet_apply(x, "daubechies", order)
    want = np.asarray(ops.stationary_wavelet_reconstruct(
        soft(np.asarray(hi_w)).astype(np.float32), lo_w,
        "daubechies", order))

    d = ops.swt_stream_delay(order, 1)
    sa = ops.swt_stream_init(order, 1)
    sr = ops.swt_stream_reconstruct_init(order, 1)
    outs = []
    for c in _chunks(x, chunk):
        sa, (hi, lo) = ops.swt_stream_step(sa, c, "daubechies", order, 1)
        sr, y = ops.swt_stream_reconstruct_step(
            sr, soft(np.asarray(hi)).astype(np.float32), lo,
            "daubechies", order, 1)
        outs.append(np.asarray(y))
    y = np.concatenate(outs)
    np.testing.assert_allclose(y[2 * d:], want[d:n - d], atol=2e-6)


def test_swt_stream_reconstruct_scan_batched(rng):
    n, chunk, order = 1024, 128, 4
    x = rng.standard_normal((3, n)).astype(np.float32)
    d = ops.swt_stream_delay(order, 1)
    sa = ops.swt_stream_init(order, 1, batch_shape=(3,))
    sr = ops.swt_stream_reconstruct_init(order, 1, batch_shape=(3,))

    def step(carry, c):
        sa, sr = carry
        sa, (hi, lo) = ops.swt_stream_step(sa, c, "daubechies", order, 1)
        sr, y = ops.swt_stream_reconstruct_step(sr, hi, lo, "daubechies",
                                                order, 1)
        return (sa, sr), y

    chunks = jnp.asarray(np.moveaxis(x.reshape(3, n // chunk, chunk), 1, 0))
    _, ys = jax.lax.scan(step, (sa, sr), chunks)
    y = np.moveaxis(np.asarray(ys), 0, 1).reshape(3, n)
    np.testing.assert_allclose(y[:, 2 * d:], x[:, d:n - d], atol=2e-6)


@pytest.mark.parametrize("nfft,hop,chunk", [(256, 64, 512), (512, 128, 512),
                                            (128, 32, 128), (64, 16, 256)])
def test_istft_stream_roundtrip(rng, nfft, hop, chunk):
    """stft_stream -> istft_stream == input delayed by nfft - hop, past
    an nfft-sample warm-up (partial window coverage at stream start)."""
    n = 4096
    x = rng.standard_normal(n, dtype=np.float32)
    d = nfft - hop
    sa = ops.stft_stream_init(nfft, hop)
    sr = ops.istft_stream_init(nfft, hop)
    outs = []
    for c in _chunks(x, chunk):
        sa, spec = ops.stft_stream_step(sa, c, nfft=nfft, hop=hop)
        sr, y = ops.istft_stream_step(sr, spec, nfft=nfft, hop=hop)
        outs.append(np.asarray(y))
    y = np.concatenate(outs)
    assert y.shape == x.shape  # one sample out per sample in
    np.testing.assert_allclose(y[nfft:], x[nfft - d:n - d], atol=2e-6)
    # the warm-up span (incomplete window coverage) emits exact zeros,
    # never attenuated partial sums (ADVICE round-1 item)
    np.testing.assert_array_equal(y[:d], np.zeros(d, np.float32))


def test_istft_stream_empty_chunk_rejected():
    """F_c == 0 fails with a clear ValueError, not an opaque IndexError."""
    sr = ops.istft_stream_init(64, 16)
    empty = np.zeros((0, 33), np.complex64)
    with pytest.raises(ValueError, match="at least one frame"):
        ops.istft_stream_step(sr, empty, nfft=64, hop=16)


def test_istft_stream_rect_unit_hop_nfft(rng):
    """hop == nfft with a rectangular window: the pair is an exact
    identity with zero latency (and the Hann zero-coverage guard emits
    0 instead of NaN)."""
    x = rng.standard_normal(1024, dtype=np.float32)
    w = np.ones(64, np.float32)
    sa = ops.stft_stream_init(64, 64)
    sr = ops.istft_stream_init(64, 64)
    outs = []
    for c in _chunks(x, 256):
        sa, spec = ops.stft_stream_step(sa, c, nfft=64, hop=64, window=w)
        sr, y = ops.istft_stream_step(sr, spec, nfft=64, hop=64, window=w)
        outs.append(np.asarray(y))
    np.testing.assert_allclose(np.concatenate(outs), x, atol=2e-6)
    # default Hann at hop==nfft: w[0]=0 -> that phase emits 0, not NaN
    sa2 = ops.stft_stream_init(64, 64)
    sr2 = ops.istft_stream_init(64, 64)
    _, spec = ops.stft_stream_step(sa2, x[:256], nfft=64, hop=64)
    _, y = ops.istft_stream_step(sr2, spec, nfft=64, hop=64)
    assert np.isfinite(np.asarray(y)).all()


def test_istft_stream_realtime_masking(rng):
    """Real-time spectral gating: stream-masked == whole-signal-masked
    (the masks see the same frames, shifted by the analysis warm-up)."""
    n, nfft, hop, chunk = 4096, 256, 64, 512
    t = np.arange(n, dtype=np.float32)
    x = (np.sin(2 * np.pi * 20.0 / 256.0 * t)
         + 1.0 * rng.standard_normal(n)).astype(np.float32)

    def mask(spec):
        mag = jnp.abs(spec)
        floor = jnp.median(mag, axis=-1, keepdims=True)
        return spec * (mag > 3.0 * floor)

    sa = ops.stft_stream_init(nfft, hop)
    sr = ops.istft_stream_init(nfft, hop)
    outs = []
    for c in _chunks(x, chunk):
        sa, spec = ops.stft_stream_step(sa, c, nfft=nfft, hop=hop)
        sr, y = ops.istft_stream_step(sr, mask(spec), nfft=nfft, hop=hop)
        outs.append(np.asarray(y))
    got = np.concatenate(outs)

    spec_w = ops.stft(x, nfft=nfft, hop=hop)
    want = np.asarray(ops.istft(mask(spec_w), nfft=nfft, hop=hop))
    # streamed output lags by d = nfft-hop. Samples before 2d still
    # overlap warm-up frames (zero-prehistory windows -> different
    # medians -> different masks than the whole-signal frames), so the
    # comparable interior starts at 2d; use 2*nfft for margin.
    d = nfft - hop
    lo, hi = 2 * nfft, n - nfft
    np.testing.assert_allclose(got[lo:hi], want[lo - d:hi - d], atol=1e-5)


def test_istft_stream_validation():
    # host numpy arrays throughout: validation must raise without any
    # device conversion (the axon tunnel lacks complex64 transfer and
    # a failed transfer poisons the backend for the rest of the run)
    st = ops.istft_stream_init(128, 32)
    with pytest.raises(ValueError, match="carry length"):
        ops.istft_stream_step(st, np.zeros((2, 65), np.complex64),
                              nfft=128, hop=64)
    with pytest.raises(ValueError, match="window length"):
        ops.istft_stream_step(st, np.zeros((2, 65), np.complex64),
                              nfft=128, hop=32, window=np.ones(64))
    with pytest.raises(ValueError, match="bins"):
        ops.istft_stream_step(st, np.zeros((2, 257), np.complex64),
                              nfft=128, hop=32)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fuzz_irregular_chunking(rng, seed):
    """Random segmentation must not change any stream's output: every
    uniform-chunk differential above, re-run with chunks of random
    lengths (the real producer case — packets arrive ragged)."""
    g = np.random.default_rng(seed)
    n = 2048
    x = rng.standard_normal(n, dtype=np.float32)
    # few cuts: each unique segment length costs a retrace
    cuts = np.sort(g.choice(np.arange(1, n), size=g.integers(3, 9),
                            replace=False))
    segs = np.split(x, cuts)

    # causal FIR (cuts are strictly interior and unique, so every
    # segment is non-empty)
    h = rng.standard_normal(21, dtype=np.float32)
    st = ops.fir_stream_init(h)
    ys = []
    for s in segs:
        st, y = ops.fir_stream_step(st, s, h)
        ys.append(np.asarray(y))
    np.testing.assert_array_equal(np.concatenate(ys),
                                  np.asarray(ops.causal_fir(x, h)))

    # SWT level 2
    d = ops.swt_stream_delay(6, 2)
    sw = ops.swt_stream_init(6, 2)
    his = []
    for s in segs:
        sw, (hi, _) = ops.swt_stream_step(sw, s, "daubechies", 6, 2)
        his.append(np.asarray(hi))
    want_hi, _ = ops.stationary_wavelet_apply(x, "daubechies", 6, level=2)
    np.testing.assert_array_equal(np.concatenate(his)[d:],
                                  np.asarray(want_hi)[:n - d])

    # peaks (positions global, union exact)
    pk = ops.peaks_stream_init()
    got_pos = []
    for s in segs:
        pk, (pos, _, cnt) = ops.peaks_stream_step(pk, s, capacity=s.size)
        got_pos.extend(np.asarray(pos)[:int(cnt)].tolist())
    wpos, _, wcnt = ops.detect_peaks_fixed(x, capacity=n - 2)
    np.testing.assert_array_equal(np.array(got_pos),
                                  np.asarray(wpos)[:int(wcnt)])


class TestWelchStream:
    @pytest.mark.parametrize("chunk", [128, 512, 1024])
    def test_final_estimate_matches_whole_signal(self, rng, chunk):
        """Feeding the whole stream reproduces ops.welch EXACTLY: the
        same real frames are averaged, warm-up frames masked."""
        n, nfft, hop = 4096, 256, 64
        x = rng.normal(size=n).astype(np.float32)
        st = ops.welch_stream_init(nfft, hop)
        est = None
        for i in range(0, n, chunk):
            st, est = ops.welch_stream_step(st, x[i:i + chunk],
                                            nfft=nfft, hop=hop)
        want = np.asarray(ops.welch(x, nfft=nfft, hop=hop))
        np.testing.assert_allclose(np.asarray(est), want, rtol=1e-5,
                                   atol=1e-9)

    def test_batched_and_running(self, rng):
        x = rng.normal(size=(3, 2048)).astype(np.float32)
        st = ops.welch_stream_init(512, 128, batch_shape=(3,))
        st, e1 = ops.welch_stream_step(st, x[:, :1024], nfft=512, hop=128)
        st, e2 = ops.welch_stream_step(st, x[:, 1024:], nfft=512, hop=128)
        assert e1.shape == e2.shape == (3, 257)
        want = np.asarray(ops.welch(x, nfft=512, hop=128))
        np.testing.assert_allclose(np.asarray(e2), want, rtol=1e-5,
                                   atol=1e-9)

    def test_warmup_only_returns_zeros(self, rng):
        """A first chunk shorter than one full frame yields no real
        frames: the estimate is zeros, not warm-up garbage."""
        st = ops.welch_stream_init(256, 64)
        st, est = ops.welch_stream_step(
            st, rng.normal(size=64).astype(np.float32), nfft=256, hop=64)
        assert int(st.n_frames) == 0
        np.testing.assert_array_equal(np.asarray(est),
                                      np.zeros(129, np.float32))
