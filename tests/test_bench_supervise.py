"""Supervisor resilience contract for bench.py (VERDICT r2 item 2).

The round-2 failure mode: a tunnel hang mid-run lost the ENTIRE perf
record — the supervisor burned a 1200 s attempt discovering the hang and
a timeout yielded nothing, not even configs that had finished. These
tests pin the two fixes with fake workers and tiny timeouts:

  * a bring-up probe hang skips straight to the error JSON (no
    full-length attempt is ever launched);
  * workers stream completed pieces to a progress file, so a kill -9 /
    timeout / crash mid-run still produces a parseable record carrying
    the headline and every finished config.
"""

import json
import os
import sys
import textwrap

# bench.py lives at the repo root (one level above tests/)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402

# generous timeouts: this box has one core, and a concurrent build or a
# parallel full-suite run can slow even a trivial python -c spawn past a
# too-tight limit (observed in the r3 TPU suite: 15 s attempts expired
# under load and the merged record lost its headline). Hang-style fake
# workers sleep 60 s, so timeouts must stay well under that.
FAST_PLANS = [(False, 40, 0), (False, 40, 0), (True, 40, 0)]
PROBE_OK = [sys.executable, "-c", "print('ok')"]
PROBE_HANG = [sys.executable, "-c", "import time; time.sleep(30)"]


def fake_worker(body: str):
    """cmd-builder running ``body`` with PROGRESS bound to the file path."""
    def build(headline_only, progress_path):
        code = ("import json, sys, time\n"
                f"PROGRESS = {progress_path!r}\n"
                f"HEADLINE_ONLY = {bool(headline_only)}\n"
                + textwrap.dedent(body))
        return [sys.executable, "-c", code]
    return build


def run_supervise(capsys, body, *, plans=FAST_PLANS, probe_cmd=PROBE_OK,
                  probe_timeout_s=10.0):
    rc = bench.supervise(plans=plans, worker_cmd=fake_worker(body),
                         probe_cmd=probe_cmd,
                         probe_timeout_s=probe_timeout_s,
                         probe_retry_sleep_s=0.0)
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, "supervisor must print exactly ONE JSON line"
    return json.loads(out[0])


HEADLINE = {"metric": "matrix_multiply_f32_n4096", "value": 123000.0,
            "unit": "GFLOPS", "vs_baseline": 1.25, "backend": "tpu"}


def test_success_passthrough(capsys):
    rec = run_supervise(capsys, f"""
        result = dict({HEADLINE!r})
        result["configs"] = {{"dwt": {{"value": 1.0}}}}
        print(json.dumps(result))
    """)
    assert rec["value"] == 123000.0
    assert rec["configs"]["dwt"]["value"] == 1.0
    assert "error" not in rec


def test_hang_merges_partial_configs(capsys):
    """Worker streams headline + 2 configs, then hangs: the record must
    carry all three pieces plus the error."""
    rec = run_supervise(capsys, f"""
        with open(PROGRESS, "a") as f:
            print(json.dumps({{"__headline__": {HEADLINE!r}}}), file=f)
            print(json.dumps({{"metric": "dwt", "value": 7.5}}), file=f)
            print(json.dumps({{"metric": "conv", "value": 3.25}}), file=f)
        time.sleep(60)
    """)
    assert rec["value"] == 123000.0          # headline survived the hang
    assert rec["configs"]["dwt"]["value"] == 7.5
    assert rec["configs"]["conv"]["value"] == 3.25
    assert "timed out" in rec["error"]


def test_crash_merges_partial(capsys):
    """kill-style death (rc=1 mid-run) still yields headline + configs."""
    rec = run_supervise(capsys, f"""
        with open(PROGRESS, "a") as f:
            print(json.dumps({{"__headline__": {HEADLINE!r}}}), file=f)
            print(json.dumps({{"metric": "dwt", "value": 7.5}}), file=f)
        sys.exit(1)
    """)
    assert rec["value"] == 123000.0
    assert rec["configs"]["dwt"]["value"] == 7.5
    assert "rc=1" in rec["error"]


def test_nothing_finished_still_one_line(capsys):
    rec = run_supervise(capsys, "sys.exit(1)\n")
    assert rec["value"] is None
    assert "error" in rec and "configs" not in rec


def test_probe_hang_skips_attempts(capsys, tmp_path):
    """A hung bring-up probe (twice) must emit the error JSON without
    launching any worker — that is the ~20 min of driver budget saved."""
    marker = tmp_path / "worker_ran"
    rec = run_supervise(capsys, f"""
        open({str(marker)!r}, "w").write("x")
        print(json.dumps({HEADLINE!r}))
    """, probe_cmd=PROBE_HANG, probe_timeout_s=0.5)
    assert rec["value"] is None
    assert "hung twice" in rec["error"]
    assert not marker.exists(), "no worker attempt may run on a dead tunnel"


def test_probe_fast_failure_still_attempts(capsys):
    """A fast probe failure (round-1 UNAVAILABLE taxonomy) must NOT gate
    the run — the plan list's retry/backoff owns that case."""
    rec = run_supervise(capsys, f"""
        print(json.dumps({HEADLINE!r}))
    """, probe_cmd=[sys.executable, "-c", "import sys; sys.exit(2)"])
    assert rec["value"] == 123000.0


def test_headline_fallback_keeps_streamed_configs(capsys):
    """Full attempts hang after streaming configs; the headline-only
    fallback succeeds — its record should still carry the streamed
    secondary configs from the failed attempts."""
    rec = run_supervise(capsys, f"""
        if HEADLINE_ONLY:
            print(json.dumps(dict({HEADLINE!r})))
        else:
            with open(PROGRESS, "a") as f:
                print(json.dumps({{"metric": "dwt", "value": 7.5}}), file=f)
            time.sleep(60)
    """)
    assert rec["value"] == 123000.0
    assert rec["configs"]["dwt"]["value"] == 7.5
    assert "headline-only" in rec["note"]


def test_attempt_spread_fields_cpu_smoke():
    """chain_stats now reports per-attempt corrected values (VERDICT r2
    item 4); the headline record carries them as ``attempts``."""
    import jax.numpy as jnp

    from veles.simd_tpu.utils.benchlib import chain_stat

    st = chain_stat(lambda c: c * 1.5, jnp.ones(64, jnp.float32),
                    iters=4, reps=2, attempts=3, on_floor="nan")
    assert len(st["attempt_sec"]) == 3
    # structural contract only: each entry is a per-attempt corrected
    # seconds (float, NaN when that window floored). The headline pairs
    # the global-min total with its own adjacent floor, so min(attempts)
    # need not equal st["sec"] under floor drift — no equality asserted.
    assert all(isinstance(s, float) for s in st["attempt_sec"])
    finite = [s for s in st["attempt_sec"] if s == s]
    assert all(s > 0 for s in finite)


def test_ref_avx_annotation():
    """Bench records self-annotate with the measured AVX baseline ratios
    when metric names match REF_BASELINE.json; non-matching or null
    records stay untouched. r4: the baseline value is no longer echoed
    per-record (line budget) — only the ratios, including the raw
    wall-clock floor ratio when a raw bound is present."""
    with open(os.path.join(os.path.dirname(bench.__file__),
                           "REF_BASELINE.json")) as f:
        cfgs = json.load(f)["configs"]
    ref_val = cfgs["matrix_multiply_f32_n4096"]["value"]
    rec = {"metric": "matrix_multiply_f32_n4096", "value": 110.4,
           "raw_value": 55.2}
    bench._annotate_ref_avx(rec)
    assert "ref_avx" not in rec  # not echoed: lives in REF_BASELINE.json
    assert rec["vs_ref_avx"] == round(110.4 / ref_val, 1)
    assert rec["vs_ref_avx_raw"] == round(55.2 / ref_val, 1)
    null_rec = {"value": None}
    bench._annotate_ref_avx(null_rec, "convolve_n65536_m127")
    assert "vs_ref_avx" not in null_rec
    missing = {"value": 5.0}
    bench._annotate_ref_avx(missing, "no_such_metric")
    assert "vs_ref_avx" not in missing
    # VERDICT r3 item 7: the convolve rows carry the FFT-path proxy
    # ceiling ratio alongside the brute-AVX floor ratio
    conv = {"value": 4199.4}
    bench._annotate_ref_avx(conv, "convolve_n65536_m127")
    assert conv["vs_ref_avx"] == round(
        4199.4 / cfgs["convolve_n65536_m127"]["value"], 1)
    assert conv["vs_ref_fft"] == round(
        4199.4 / cfgs["convolve_n65536_m127_fft_proxy"]["value"], 1)


def test_failed_leg_isolated():
    """One leg of a multi-leg chain_stats config failing to compile
    (e.g. the FFT leg during the r3 tunnel capability outage) reports
    an error entry for that leg while the surviving legs time normally;
    a failing null chain would abort instead."""
    import jax.numpy as jnp

    from veles.simd_tpu.utils.benchlib import chain_stats

    def ok(c):
        return c * jnp.float32(1.0000001)

    def broken(c):
        raise RuntimeError("backend capability out")

    carry = jnp.ones((4, 256), jnp.float32)
    sts = chain_stats({"good": ok, "bad": broken}, carry, iters=4,
                      reps=1, on_floor="nan", null_carry=carry[:1, :8])
    assert "error" in sts["bad"]
    assert sts["bad"]["sec"] != sts["bad"]["sec"]  # NaN
    assert "error" not in sts["good"]
    assert sts["good"]["raw_sec"] > 0


def test_nonfinite_leg_isolated():
    """A leg whose warm-up checksum is non-finite (a backend computing
    garbage, r3 FFT outage mode 2) is isolated with the reason recorded,
    not allowed to kill its siblings."""
    import jax.numpy as jnp

    from veles.simd_tpu.utils.benchlib import chain_stats

    def ok(c):
        return c * jnp.float32(1.0000001)

    def poison(c):
        return c * jnp.float32(float("nan"))

    carry = jnp.ones((4, 256), jnp.float32)
    sts = chain_stats({"good": ok, "bad": poison}, carry, iters=4,
                      reps=1, on_floor="nan", null_carry=carry[:1, :8])
    assert "non-finite" in sts["bad"]["error"]
    assert "error" not in sts["good"]
    assert sts["good"]["raw_sec"] > 0
