"""Golden-value tests for the NumPy float64 oracle.

The expected vectors are lifted from the reference test suite (values are
test *data*, reused per SURVEY §4): tests/convolve.cc:53-71,
tests/correlate.cc:53-71, tests/wavelet.cc:88-167, tests/detect_peaks.cc:41-98,
tests/normalize.cc:42-65. If the oracle reproduces these, the reference's
scalar `_na` semantics were captured faithfully; every TPU implementation is
then tested differentially against the oracle.
"""

import numpy as np
import pytest

from veles.simd_tpu.reference import (arithmetic, convolve, correlate,
                                      detect_peaks, mathfun, matrix,
                                      normalize, wavelet)


def test_convolve_golden():
    x = np.array([1, 2, 3, 4, 5, 6, 7, 8], dtype=np.float64)
    h = np.array([10, 9, 8, 7], dtype=np.float64)
    expected = [10, 29, 56, 90, 124, 158, 192, 226, 170, 113, 56]
    np.testing.assert_allclose(convolve.convolve(x, h), expected, atol=1e-4)


def test_cross_correlate_golden():
    x = np.array([1, 2, 3, 4, 5, 6, 7, 8], dtype=np.float64)
    h = np.array([10, 9, 8, 7], dtype=np.float64)
    expected = [7, 22, 46, 80, 114, 148, 182, 216, 187, 142, 80]
    np.testing.assert_allclose(correlate.cross_correlate(x, h), expected,
                               atol=1e-4)


VALID_DESTLO_DB8 = [
    1.42184071797210, 4.25026784271829, 7.07869496746448, 9.90712209221067,
    12.7355492169569, 15.5639763417030, 18.3924034664492, 21.2208305911954,
    24.0492577159416, 26.8776848406878, 29.7061119654340, 32.5345390901802,
    35.3629662149264, 37.4782538234490, 45.3048707044478, 28.8405938767906]

VALID_DESTHI_DB8 = [
    -9.91075277401166e-13, -9.90367510222967e-13, -9.90194037875369e-13,
    -9.91873250200115e-13, -9.91456916565880e-13, -9.91096094082877e-13,
    -9.90263426814408e-13, -9.89069937062936e-13, -9.91706716746421e-13,
    -9.92234072683118e-13, -9.92872450922278e-13, -9.91484672141496e-13,
    -9.88431558823777e-13, -15.5030002317990, 5.58066496329142,
    -1.39137323046436]


def test_wavelet_apply_golden_db8():
    # tests/wavelet.cc:88-112 — ramp 0..31, Daubechies-8, periodic extension.
    src = np.arange(32, dtype=np.float64)
    hi, lo = wavelet.wavelet_apply(src, "daubechies", 8, "periodic")
    np.testing.assert_allclose(lo, VALID_DESTLO_DB8, atol=1e-5)
    np.testing.assert_allclose(hi, VALID_DESTHI_DB8, atol=1e-5)


VALID_SWT_DESTLO_L2 = [
    6.03235928067132, 8.03235928067132, 10.0323592806713, 12.0323592806713,
    14.0323592806713, 16.0323592806713, 18.0323592806713, 20.0323592806713,
    22.0323592806713, 24.0323592806713, 26.0323592806713, 28.0287655230843,
    30.0399167066535, 32.0615267227001, 33.9634987065767, 35.9320147305194,
    38.3103125658258, 40.4883104236778, 42.2839848729069, 43.7345002903498,
    43.7794736932925, 45.1480484137191, 49.8652419127137, 55.7384062022009,
    62.7058766150960, 65.2835749751486, 58.7895581326311, 46.7708694321525,
    31.0673425771182, 16.9214616227404, 9.00063853315767, 5.73072526035035]

VALID_SWT_DESTHI2 = [
    -2.80091227988777e-12, -2.79960776783383e-12, -2.80357681514687e-12,
    -2.80355599846516e-12, -2.80095391325119e-12, -2.79949674553137e-12,
    -2.79951062331918e-12, -2.80001022368026e-12, -2.80267475893936e-12,
    -2.79856693374825e-12, -2.80492296056423e-12, -0.0781250000022623,
    0.164291522328916, 0.634073488075181, -1.49696584171718,
    -2.62270640553024, 6.97048991951669, 13.4936761845669, -2.98585954495631,
    -19.8119363515072, -12.7098068594040, 1.52245837263813, 7.82528131630407,
    8.59130932663576, 5.24090543738087, 1.01894438076528, -1.16818198731391,
    -1.89266864772546, -1.51961243979140, -0.776900347899835,
    -0.320541522330983, -0.0781250000022604]


def test_stationary_wavelet_apply_golden_db8():
    # tests/wavelet.cc:117-167 — two cascaded SWT levels on a ramp.
    src = np.arange(32, dtype=np.float64)
    hi1, lo1 = wavelet.stationary_wavelet_apply(src, "daubechies", 8, 1,
                                                "periodic")
    hi2, lo2 = wavelet.stationary_wavelet_apply(lo1, "daubechies", 8, 2,
                                                "periodic")
    np.testing.assert_allclose(hi2, VALID_SWT_DESTHI2, atol=1e-5)
    np.testing.assert_allclose(lo2, VALID_SWT_DESTLO_L2, atol=1e-5)


def test_detect_peaks_sine_golden():
    # tests/detect_peaks.cc:41-74.
    data = np.sin(np.arange(4000, dtype=np.float32) * np.pi / 100)
    pos, val = detect_peaks.detect_peaks(data, detect_peaks.EXTREMUM_TYPE_MAXIMUM)
    assert len(pos) == 20
    np.testing.assert_array_equal(pos, np.arange(20) * 200 + 50)
    np.testing.assert_allclose(val, 1.0, rtol=1e-6)

    pos, val = detect_peaks.detect_peaks(data, detect_peaks.EXTREMUM_TYPE_MINIMUM)
    np.testing.assert_array_equal(pos, np.arange(20) * 200 + 150)
    np.testing.assert_allclose(val, -1.0, rtol=1e-6)

    pos, val = detect_peaks.detect_peaks(data, detect_peaks.EXTREMUM_TYPE_BOTH)
    assert len(pos) == 40
    np.testing.assert_array_equal(
        pos, (np.arange(40) // 2) * 200 + 50 + 100 * (np.arange(40) % 2))


def test_detect_peaks_nasty_golden():
    # tests/detect_peaks.cc:76-98: isolated unit spikes, incl. near the end.
    data = np.zeros(101, dtype=np.float32)
    data[[7, 16, 97, 99]] = 1
    pos, val = detect_peaks.detect_peaks(data, detect_peaks.EXTREMUM_TYPE_MAXIMUM)
    np.testing.assert_array_equal(pos, [7, 16, 97, 99])
    np.testing.assert_allclose(val, 1.0)


def test_normalize2D_golden():
    # tests/normalize.cc:42-65: stride-128 uint8 plane viewed at width 100.
    array = np.ones((100, 128), dtype=np.uint8)
    array[0, 0] = 127
    array[0, 1] = 15
    array[0, 10] = 252
    array[0, 89] = 31
    array[1, 21] = 3
    view = array[:, :100]  # src_stride=128, width=100
    res = normalize.normalize2D(view)
    assert res.shape == (100, 100)
    np.testing.assert_allclose(res[0, 0], 2.0 * (127 - 1) / 251 - 1, rtol=1e-6)
    np.testing.assert_allclose(res[0, 1], 2.0 * (15 - 1) / 251 - 1, rtol=1e-6)
    np.testing.assert_allclose(res[0, 2], -1.0)
    np.testing.assert_allclose(res[0, 10], 1.0)
    np.testing.assert_allclose(res[0, 89], 2.0 * (31 - 1) / 251 - 1, rtol=1e-6)
    np.testing.assert_allclose(res[1, 21], 2.0 * (3 - 1) / 251 - 1, rtol=1e-6)


def test_normalize_degenerate():
    flat = np.full((4, 4), 7, dtype=np.uint8)
    np.testing.assert_array_equal(normalize.normalize2D(flat), 0.0)


def test_matrix_golden():
    # tests/matrix.cc:128-141 style: small validated multiply.
    m1 = np.array([[1.0, 2.0], [3.0, 4.0]])
    m2 = np.array([[5.0, 6.0], [7.0, 8.0]])
    np.testing.assert_array_equal(matrix.matrix_multiply(m1, m2),
                                  [[19, 22], [43, 50]])
    np.testing.assert_array_equal(matrix.matrix_multiply_transposed(m1, m2),
                                  [[17, 23], [39, 53]])
    np.testing.assert_array_equal(matrix.matrix_add(m1, m2), m1 + m2)
    np.testing.assert_array_equal(matrix.matrix_sub(m1, m2), m1 - m2)
    with pytest.raises(ValueError):
        matrix.matrix_multiply(np.zeros((2, 3)), np.zeros((2, 3)))


def test_arithmetic_roundtrips(rng):
    i16 = rng.integers(-(2 ** 15), 2 ** 15 - 1, 1000, dtype=np.int16)
    np.testing.assert_array_equal(
        arithmetic.float_to_int16(arithmetic.int16_to_float(i16)), i16)
    f = rng.normal(size=1000).astype(np.float32) * 100
    np.testing.assert_array_equal(arithmetic.float_to_int16(f),
                                  np.trunc(f).astype(np.int16))
    # interleaved complex multiply against numpy complex
    a = rng.normal(size=64)
    b = rng.normal(size=64)
    got = arithmetic.complex_multiply(a, b)
    want = (a.view(np.complex128) * b.view(np.complex128)).view(np.float64)
    np.testing.assert_allclose(got, want)
    got = arithmetic.complex_multiply_conjugate(a, b)
    want = (a.view(np.complex128) * np.conj(b.view(np.complex128))).view(np.float64)
    np.testing.assert_allclose(got, want)
    # widening int16 multiply
    x = np.array([-30000, 30000, 123], dtype=np.int16)
    y = np.array([2, 2, -3], dtype=np.int16)
    np.testing.assert_array_equal(arithmetic.int16_multiply(x, y),
                                  [-60000, 60000, -369])


def test_mathfun_oracle(rng):
    x = rng.normal(size=256)
    np.testing.assert_allclose(mathfun.sin_psv(x), np.sin(x))
    np.testing.assert_allclose(mathfun.exp_psv(x), np.exp(x))
    np.testing.assert_allclose(mathfun.cos_psv(x), np.cos(x))
    np.testing.assert_allclose(mathfun.log_psv(np.abs(x) + 0.1),
                               np.log(np.abs(x) + 0.1))


def test_wavelet_extension_modes():
    src = np.array([1.0, 2.0, 3.0])
    np.testing.assert_array_equal(wavelet.extension(src, 4, "periodic"),
                                  [1, 2, 3, 1])
    np.testing.assert_array_equal(wavelet.extension(src, 4, "mirror"),
                                  [3, 2, 1, 3])
    np.testing.assert_array_equal(wavelet.extension(src, 4, "constant"),
                                  [3, 3, 3, 3])
    np.testing.assert_array_equal(wavelet.extension(src, 4, "zero"),
                                  [0, 0, 0, 0])
