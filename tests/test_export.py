"""AOT export (utils/export.py): serialized-artifact parity.

The deployment analogue of the reference's NDK cross-build
(android/Android.mk.in): an op lowered + serialized on one machine must
reproduce the live op's output when reloaded, including on a lowering
target chosen at export time and for symbolic (length-generic) shapes.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veles.simd_tpu import ops
from veles.simd_tpu.utils import export as vexport


def test_roundtrip_matmul(tmp_path, rng):
    m1 = rng.standard_normal((64, 32), dtype=np.float32)
    m2 = rng.standard_normal((32, 48), dtype=np.float32)
    p = vexport.save_op(tmp_path / "mm.stablehlo", ops.matrix_multiply,
                        (jax.ShapeDtypeStruct((64, 32), jnp.float32),
                         jax.ShapeDtypeStruct((32, 48), jnp.float32)))
    op = vexport.load_op(p)
    np.testing.assert_allclose(np.asarray(op(m1, m2)),
                               np.asarray(ops.matrix_multiply(m1, m2)),
                               rtol=1e-6)


def test_roundtrip_convolve(tmp_path, rng):
    x = rng.standard_normal(512, dtype=np.float32)
    h = rng.standard_normal(31, dtype=np.float32)
    p = vexport.save_op(tmp_path / "conv.stablehlo",
                        lambda x, h: ops.convolve(x, h),
                        (jax.ShapeDtypeStruct((512,), jnp.float32),
                         jax.ShapeDtypeStruct((31,), jnp.float32)))
    op = vexport.load_op(p)
    np.testing.assert_allclose(np.asarray(op(x, h)),
                               np.asarray(ops.convolve(x, h)),
                               rtol=1e-4, atol=1e-4)


def test_symbolic_length(tmp_path, rng):
    """One artifact, every length — sym('n') plays the role of the
    reference's length-generic C loop (mathfun.h:142-204)."""
    p = vexport.save_op(tmp_path / "sin.stablehlo", ops.sin_psv,
                        (vexport.sym("n"),))
    op = vexport.load_op(p)
    for n in (8, 129, 1000):
        x = rng.standard_normal(n, dtype=np.float32)
        np.testing.assert_allclose(np.asarray(op(x)), np.sin(x),
                                   rtol=2e-5, atol=2e-6)


def test_symbolic_multi_arg(tmp_path, rng):
    """Two symbolic operands sharing dimensions — syms() builds them in
    one scope so (m,k)·(k,n) exports once and serves any size triple."""
    p = vexport.save_op(tmp_path / "mm.stablehlo", ops.matrix_multiply,
                        vexport.syms("m, k", "k, n"))
    op = vexport.load_op(p)
    for (m, k, n) in ((4, 8, 4), (33, 65, 17)):
        m1 = rng.standard_normal((m, k), dtype=np.float32)
        m2 = rng.standard_normal((k, n), dtype=np.float32)
        np.testing.assert_allclose(
            np.asarray(op(m1, m2)),
            np.asarray(ops.matrix_multiply(m1, m2)), rtol=1e-5, atol=1e-5)


def test_cross_platform_lowering(tmp_path):
    """Export for {cpu, tpu} from whatever host runs the tests — the NDK
    cross-compile axis. The artifact must load and run on the current
    backend because it is among the lowered platforms."""
    p = vexport.save_op(
        tmp_path / "wav.stablehlo",
        lambda x: ops.wavelet_apply(x, "daubechies", 8),
        (jax.ShapeDtypeStruct((256,), jnp.float32),),
        platforms=["cpu", "tpu"])
    op = vexport.load_op(p)
    assert set(op.exported.platforms) == {"cpu", "tpu"}
    x = np.sin(np.arange(256, dtype=np.float32))
    hi, lo = ops.wavelet_apply(x, "daubechies", 8)
    got_hi, got_lo = op(x)
    np.testing.assert_allclose(np.asarray(got_hi), np.asarray(hi), atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_lo), np.asarray(lo), atol=1e-5)


def test_bundle_roundtrip(tmp_path, rng):
    bundle_ops = {
        "exp": (ops.exp_psv,
                (jax.ShapeDtypeStruct((128,), jnp.float32),)),
        "madd": (ops.matrix_add,
                 (jax.ShapeDtypeStruct((8, 8), jnp.float32),
                  jax.ShapeDtypeStruct((8, 8), jnp.float32))),
    }
    path = vexport.save_bundle(tmp_path / "bundle", bundle_ops)
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    assert set(manifest["ops"]) == {"exp", "madd"}
    assert all((tmp_path / "bundle" / e["file"]).exists()
               for e in manifest["ops"].values())

    loaded = vexport.load_bundle(path)
    x = rng.standard_normal(128, dtype=np.float32) * 0.5
    np.testing.assert_allclose(np.asarray(loaded["exp"](x)), np.exp(x),
                               rtol=2e-5)
    m = rng.standard_normal((8, 8), dtype=np.float32)
    np.testing.assert_allclose(np.asarray(loaded["madd"](m, m)), m + m,
                               rtol=1e-6)


def test_standard_bundle(tmp_path, rng):
    """The 'product build': flagship ops at deployment shapes all export,
    reload, and agree with the live implementations."""
    path = vexport.standard_bundle(tmp_path / "dist", length=1024,
                                   batch=4, n=64)
    loaded = vexport.load_bundle(path)
    assert len(loaded) == 17

    x = rng.standard_normal(1024, dtype=np.float32)
    # round-2 families round-trip too
    got_rs = np.asarray(loaded["resample_3_2"](x))
    want_rs = np.asarray(ops.resample_poly(x, 3, 2))
    np.testing.assert_allclose(got_rs, want_rs, atol=1e-5)
    xb = rng.standard_normal((4, 1024), dtype=np.float32)
    sos = ops.butter_sos(6, 0.2)
    got_sf = np.asarray(loaded["sosfilt_butter6"](xb))
    want_sf = np.asarray(ops.sosfilt(xb, sos))
    np.testing.assert_allclose(got_sf, want_sf, atol=1e-5)
    hi, lo = ops.wavelet_apply(x, "daubechies", 8)
    got_hi, got_lo = loaded["wavelet_apply_db8"](x)
    np.testing.assert_allclose(np.asarray(got_hi), np.asarray(hi), atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_lo), np.asarray(lo), atol=1e-5)

    h = rng.standard_normal(127, dtype=np.float32)
    np.testing.assert_allclose(np.asarray(loaded["convolve"](x, h)),
                               np.asarray(ops.convolve(x, h)),
                               rtol=1e-3, atol=1e-3)
    # round-3 families round-trip: conditioned peaks + Welch + scalogram
    pos, val, count, _ = loaded["find_peaks_conditioned"](x)
    wpos, wval, wcount, _ = ops.find_peaks_fixed(
        x, capacity=64, height=0.0, distance=8.0, prominence=0.1)
    np.testing.assert_array_equal(np.asarray(pos), np.asarray(wpos))
    np.testing.assert_allclose(np.asarray(val), np.asarray(wval),
                               atol=1e-6)
    assert int(count) == int(wcount)
    np.testing.assert_allclose(
        np.asarray(loaded["welch_psd"](xb)),
        np.asarray(ops.welch(xb, nfft=512, detrend="constant")),
        rtol=1e-4, atol=1e-7)
    scales = tuple(float(s) for s in np.geomspace(2, 32, 8))
    np.testing.assert_allclose(
        np.asarray(loaded["cwt_ricker_8scales"](x)),
        np.asarray(ops.cwt(x, scales)), atol=1e-5)


def test_exported_artifact_is_self_contained(tmp_path):
    """The artifact must not consult this package at call time: loading
    happens through jax.export.deserialize alone. Guard by checking the
    file is plain bytes that deserialize without touching our op modules
    (a monkeypatched-out implementation cannot change the result)."""
    import veles.simd_tpu.ops.mathfun as mathfun_mod
    p = vexport.save_op(tmp_path / "c.stablehlo", ops.cos_psv,
                        (jax.ShapeDtypeStruct((64,), jnp.float32),))
    op = vexport.load_op(p)
    x = np.linspace(-3, 3, 64, dtype=np.float32)
    want = np.asarray(op(x))

    orig = mathfun_mod.cos_psv
    try:
        mathfun_mod.cos_psv = None  # break the live op
        again = np.asarray(op(x))
    finally:
        mathfun_mod.cos_psv = orig
    np.testing.assert_array_equal(want, again)
    np.testing.assert_allclose(want, np.cos(x), rtol=2e-5, atol=2e-6)


def test_sym_spec_shapes():
    s = vexport.sym("b, 2*n")
    assert len(s.shape) == 2
    assert s.dtype == jnp.float32
    with pytest.raises(Exception):
        vexport.sym("not a ! valid @ spec")
