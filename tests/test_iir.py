"""IIR (biquad cascade) suite: the associative-scan formulation vs the
float64 scipy oracle, plus streaming exactness and design helpers."""

import numpy as np
import pytest

from veles.simd_tpu import ops
from veles.simd_tpu.reference import iir as ref_iir


def _sos(order=4, wn=0.2, btype="lowpass"):
    return ops.butter_sos(order, wn, btype)


class TestSosfilt:
    @pytest.mark.parametrize("order,wn,btype", [(2, 0.1, "lowpass"),
                                                (4, 0.25, "highpass"),
                                                (6, 0.3, "lowpass"),
                                                (5, 0.15, "lowpass")])
    def test_differential(self, rng, order, wn, btype):
        x = rng.normal(size=512).astype(np.float32)
        sos = _sos(order, wn, btype)
        want = ref_iir.sosfilt(x, sos)
        got = np.asarray(ops.sosfilt(x, sos))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_bandpass(self, rng):
        x = rng.normal(size=1024).astype(np.float32)
        sos = ops.butter_sos(4, [0.2, 0.4], "bandpass")
        want = ref_iir.sosfilt(x, sos)
        got = np.asarray(ops.sosfilt(x, sos))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_batched(self, rng):
        x = rng.normal(size=(3, 4, 300)).astype(np.float32)
        sos = _sos()
        got = np.asarray(ops.sosfilt(x, sos))
        want = ref_iir.sosfilt(x, sos)
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("n", [8192, 8192 + 1000, 3 * 4096])
    def test_chunked_equals_flat(self, rng, n):
        """The blocked formulation (auto-picked at n >= 2*4096, VERDICT
        r2 item 5) must equal the flat tree to reassociation tolerance —
        including a sub-chunk remainder and an exact block multiple."""
        x = rng.normal(size=(2, n)).astype(np.float32)
        sos = _sos(4, 0.2)
        flat = np.asarray(ops.sosfilt(x, sos, chunk=0))
        auto = np.asarray(ops.sosfilt(x, sos))          # policy: chunked
        forced = np.asarray(ops.sosfilt(x, sos, chunk=1024))
        np.testing.assert_allclose(auto, flat, rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(forced, flat, rtol=2e-5, atol=2e-5)
        # and against the float64 oracle, the usual differential bound
        want = ref_iir.sosfilt(x, sos)
        np.testing.assert_allclose(auto, want, rtol=1e-4, atol=1e-4)

    def test_blockbasis_many_blocks_and_states(self, rng):
        """The r4 block-basis superposition path (one parallel tree over
        all blocks + 2-vector state chain): many blocks, a sub-chunk
        remainder, and a NONZERO incoming state — the superposition
        correction and the state chain must reproduce the flat tree
        exactly (states) / to reassociation tolerance (samples)."""
        from veles.simd_tpu.ops.iir import _sosfilt_xla
        sos = np.asarray(_sos(6, 0.25), np.float32)
        S = sos.shape[0]
        x = rng.normal(size=(3, 19 * 1024 + 357)).astype(np.float32)
        s0 = (rng.normal(size=(S, 2)) * 0.1).astype(np.float32)
        y_bb, sf_bb = _sosfilt_xla(x, sos, s0, S, chunk=1024)
        y_fl, sf_fl = _sosfilt_xla(x, sos, s0, S, chunk=0)
        np.testing.assert_allclose(np.asarray(y_bb), np.asarray(y_fl),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(sf_bb), np.asarray(sf_fl),
                                   rtol=2e-5, atol=2e-5)

    def test_chunked_final_state_matches_flat(self, rng):
        """Streaming correctness hinges on the scanned-out final state:
        chain two chunked whole-signal calls via iir_stream_step and
        compare against one flat call (remainder tail exercised)."""
        n = 2 * 4096 + 777
        x = rng.normal(size=n).astype(np.float32)
        sos = _sos(4, 0.25)
        st = ops.iir_stream_init(sos)
        st, y1 = ops.iir_stream_step(st, x[:8192], sos)   # chunked path
        st, y2 = ops.iir_stream_step(st, x[8192:], sos)   # flat path
        got = np.concatenate([np.asarray(y1), np.asarray(y2)])
        want = np.asarray(ops.sosfilt(x, sos, chunk=0))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_lowpass_attenuates_high_tone(self):
        n = 2048
        t = np.arange(n, dtype=np.float64)
        lo_tone = np.sin(2 * np.pi * 0.02 * t).astype(np.float32)
        hi_tone = np.sin(2 * np.pi * 0.45 * t).astype(np.float32)
        sos = _sos(6, 0.2)
        y_lo = np.asarray(ops.sosfilt(lo_tone, sos))
        y_hi = np.asarray(ops.sosfilt(hi_tone, sos))
        # steady-state amplitudes: passband ~unity, stopband crushed
        assert np.std(y_lo[500:]) > 0.6
        assert np.std(y_hi[500:]) < 0.01

    def test_sos_contracts(self):
        with pytest.raises(ValueError):
            ops.sosfilt(np.zeros(8, np.float32),
                        np.zeros((2, 5), np.float32))
        bad = np.zeros((1, 6), np.float32)
        bad[0, 3] = 2.0  # a0 != 1
        with pytest.raises(ValueError, match="normalized"):
            ops.sosfilt(np.zeros(8, np.float32), bad)


class TestIirStream:
    @pytest.mark.parametrize("chunk", [64, 100, 256])
    def test_concat_matches_whole(self, rng, chunk):
        n = chunk * 5
        x = rng.normal(size=n).astype(np.float32)
        sos = _sos(4, 0.2)
        st = ops.iir_stream_init(sos)
        outs = []
        for i in range(0, n, chunk):
            st, y = ops.iir_stream_step(st, x[i:i + chunk], sos)
            outs.append(np.asarray(y))
        got = np.concatenate(outs)
        want = np.asarray(ops.sosfilt(x, sos))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_state_matches_scipy_zi(self, rng):
        """The carried state IS scipy's zi: filtering a chunk with our
        final state as scipy's initial state continues the stream."""
        x = rng.normal(size=256).astype(np.float32)
        sos = _sos(4, 0.3)
        st = ops.iir_stream_init(sos)
        st, y1 = ops.iir_stream_step(st, x[:128], sos)
        want2, _ = ref_iir.sosfilt(x[128:], sos,
                                   zi=np.asarray(st.state))
        _, got2 = ops.iir_stream_step(st, x[128:], sos)
        np.testing.assert_allclose(np.asarray(got2), np.ravel(want2),
                                   rtol=1e-4, atol=1e-4)

    def test_batched_stream(self, rng):
        x = rng.normal(size=(3, 200)).astype(np.float32)
        sos = _sos(3, 0.25)
        st = ops.iir_stream_init(sos, batch_shape=(3,))
        st, y1 = ops.iir_stream_step(st, x[:, :100], sos)
        st, y2 = ops.iir_stream_step(st, x[:, 100:], sos)
        got = np.concatenate([np.asarray(y1), np.asarray(y2)], axis=-1)
        want = np.asarray(ops.sosfilt(x, sos))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_unbatched_state_broadcasts_over_batched_chunk(self, rng):
        # an (n_sections, 2) state from the default iir_stream_init()
        # must broadcast across a batched chunk (regression: the r3
        # time-leading rewrite briefly reshaped the state without
        # broadcasting first, raising from inside jit)
        x = rng.normal(size=(2, 300)).astype(np.float32)
        sos = _sos(3, 0.25)
        st = ops.iir_stream_init(sos)  # batch_shape=()
        st2, y = ops.iir_stream_step(st, x, sos)
        assert y.shape == x.shape
        assert st2.state.shape == (2, sos.shape[0], 2)
        want = np.asarray(ops.sosfilt(x, sos))
        np.testing.assert_allclose(np.asarray(y), want, rtol=1e-4,
                                   atol=1e-4)

    def test_state_shape_contract(self):
        sos = _sos(4, 0.2)
        st = ops.iir_stream_init(sos)
        other = _sos(2, 0.2)
        with pytest.raises(ValueError, match="sections"):
            ops.iir_stream_step(st, np.zeros(16, np.float32), other)


class TestSosfiltfilt:
    def test_zero_phase_tone(self):
        # a passband tone comes back with no phase shift (the forward
        # pass alone delays it)
        n = 4096
        t = np.arange(n, dtype=np.float64)
        x = np.sin(2 * np.pi * 0.02 * t).astype(np.float32)
        sos = ops.butter_sos(4, 0.2)
        y = np.asarray(ops.sosfiltfilt(x, sos))
        fwd = np.asarray(ops.sosfilt(x, sos))
        mid = slice(1000, 3000)
        # zero-phase: correlates best at lag 0; forward-only does not
        def best_lag(sig):
            lags = range(-40, 41)
            return max(lags, key=lambda L: float(
                np.dot(sig[mid], np.roll(x, L)[mid])))
        assert best_lag(y) == 0
        assert best_lag(fwd) != 0

    def test_matches_reference(self, rng):
        x = rng.normal(size=(2, 512)).astype(np.float32)
        sos = ops.butter_sos(4, 0.3)
        want = ops.sosfiltfilt(x, sos, impl="reference")
        got = np.asarray(ops.sosfiltfilt(x, sos))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


class TestIirFuzz:
    """Random filter designs x random shapes vs the float64 oracle —
    the adversarial-shape differential pattern (test_convolve.py's
    TestAlgorithmEquivalenceFuzz applied to the IIR family)."""

    @pytest.mark.parametrize("seed", range(6))
    def test_random_designs_agree(self, seed):
        g = np.random.default_rng(3000 + seed)
        order = int(g.integers(1, 9))
        wn = float(g.uniform(0.05, 0.45))
        btype = ("lowpass", "highpass")[int(g.integers(0, 2))]
        n = int(g.integers(16, 3000))
        x = g.normal(size=n).astype(np.float32)
        sos = ops.butter_sos(order, wn, btype)
        want = ref_iir.sosfilt(x, sos)
        got = np.asarray(ops.sosfilt(x, sos))
        scale = np.abs(want).max() + 1.0
        np.testing.assert_allclose(
            got / scale, want / scale, atol=5e-5,
            err_msg=f"seed={seed} order={order} wn={wn:.3f} "
                    f"{btype} n={n}")

    @pytest.mark.parametrize("seed", range(4))
    def test_random_chunking_agrees(self, seed):
        g = np.random.default_rng(4000 + seed)
        n = 1024
        x = g.normal(size=n).astype(np.float32)
        sos = ops.butter_sos(int(g.integers(2, 7)),
                             float(g.uniform(0.1, 0.4)))
        cuts = np.sort(g.choice(np.arange(1, n),
                                size=int(g.integers(2, 6)),
                                replace=False))
        st = ops.iir_stream_init(sos)
        outs = []
        for seg in np.split(x, cuts):
            st, y = ops.iir_stream_step(st, seg, sos)
            outs.append(np.asarray(y))
        got = np.concatenate(outs)
        want = np.asarray(ops.sosfilt(x, sos))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


class TestLfilter:
    @pytest.mark.parametrize("order,wn", [(2, 0.1), (4, 0.25), (6, 0.3)])
    def test_iir_differential(self, rng, order, wn):
        """(b, a) path vs scipy.signal.lfilter float64: the tf2sos
        cascade must match the direct form for stable filters."""
        from scipy.signal import butter, lfilter as sp_lfilter

        b, a = butter(order, wn)
        x = rng.normal(size=(3, 700)).astype(np.float32)
        want = sp_lfilter(b, a, x.astype(np.float64), axis=-1)
        got = np.asarray(ops.lfilter(b, a, x))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_fir_path(self, rng):
        """len(a)==1 runs as trimmed causal convolution."""
        from scipy.signal import lfilter as sp_lfilter

        b = rng.normal(size=17).astype(np.float64)
        x = rng.normal(size=300).astype(np.float32)
        want = sp_lfilter(b, [2.0], x.astype(np.float64))
        got = np.asarray(ops.lfilter(b, [2.0], x))
        assert got.shape == x.shape
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_reference_impl_and_contracts(self, rng):
        from scipy.signal import butter

        b, a = butter(4, 0.2)
        x = rng.normal(size=128).astype(np.float32)
        ref = ops.lfilter(b, a, x, impl="reference")
        got = np.asarray(ops.lfilter(b, a, x))
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
        with pytest.raises(ValueError):
            ops.lfilter(b, [0.0], x)  # a[0] == 0
        with pytest.raises(ValueError):
            ops.lfilter(np.zeros((2, 2)), a, x)  # non-1-D b


class TestDecimate:
    @pytest.mark.parametrize("q", [2, 4, 7])
    def test_interior_matches_scipy(self, rng, q):
        """Interior samples match scipy.signal.decimate (zero_phase);
        the unpadded sosfiltfilt makes the edge spans differ by
        construction (see sosfiltfilt docstring)."""
        from scipy.signal import decimate as sp_decimate

        n = 4096
        x = rng.normal(size=n).astype(np.float32)
        want = sp_decimate(x.astype(np.float64), q)
        got = np.asarray(ops.decimate(x, q))
        assert got.shape == want.shape
        m = len(got)
        sl = slice(m // 8, -m // 8)  # away from both transients
        np.testing.assert_allclose(got[sl], want[sl], rtol=2e-3,
                                   atol=2e-3)

    def test_q1_identity_and_contracts(self, rng):
        x = rng.normal(size=64).astype(np.float32)
        np.testing.assert_array_equal(np.asarray(ops.decimate(x, 1)), x)
        with pytest.raises(ValueError):
            ops.decimate(x, 0)

    def test_aliasing_suppressed(self):
        """A tone above the post-decimation Nyquist must not fold back."""
        n, q = 8192, 4
        t = np.arange(n)
        hi = np.sin(2 * np.pi * 0.35 * t).astype(np.float32)  # > 1/(2q)
        got = np.asarray(ops.decimate(hi, q))
        assert np.std(got[200:-200]) < 0.02


class TestSosfreqz:
    def test_matches_scipy(self):
        sos = _sos(6, 0.25)
        w_ref, h_ref = ops.sosfreqz(sos, 256, impl="reference")
        w, h = ops.sosfreqz(sos, 256)
        np.testing.assert_allclose(np.asarray(w), w_ref, atol=1e-6)
        np.testing.assert_allclose(np.asarray(h), h_ref, atol=1e-4)

    def test_high_order_stopband_accuracy(self):
        """Order-12 cascade, deep stopband: the float64 host evaluation
        (ADVICE r2) must hold RELATIVE accuracy against scipy where the
        magnitude sits ~100 dB down — complex64 per-section products
        could not."""
        sos = _sos(12, 0.2)
        w_ref, h_ref = ops.sosfreqz(sos, 1024, impl="reference")
        w, h = ops.sosfreqz(sos, 1024)
        stop = w_ref > 0.6 * np.pi  # deep stopband bins
        assert np.abs(h_ref[stop]).max() < 1e-4  # the regime under test
        np.testing.assert_allclose(np.asarray(h)[stop], h_ref[stop],
                                   rtol=1e-9)

    def test_filter_matches_response(self, rng):
        """|H| at a tone's frequency predicts sosfilt's steady-state
        gain — closes the design->filter->verify loop."""
        sos = _sos(6, 0.3)
        f = 0.1  # cycles/sample; passband
        n = 8192
        x = np.sin(2 * np.pi * f * np.arange(n)).astype(np.float32)
        y = np.asarray(ops.sosfilt(x, sos))
        gain = np.std(y[2000:]) / np.std(x[2000:])
        w, h = ops.sosfreqz(sos, 4096)
        # grid excludes pi: bin k is at w = pi*k/4096
        hi = np.abs(np.asarray(h))[int(round(f * 2 * 4096))]
        np.testing.assert_allclose(gain, hi, rtol=1e-2)


def test_filtfilt_zero_phase(rng):
    """(b, a) zero-phase twin: matches sosfiltfilt through tf2sos away
    from the edge transients, and cancels group delay on a tone."""
    from scipy.signal import butter

    b, a = butter(4, 0.25)
    x = rng.normal(size=(2, 2048)).astype(np.float32)
    got = np.asarray(ops.filtfilt(b, a, x))
    want = np.asarray(ops.sosfiltfilt(x, ops.tf2sos(b, a)))
    mid = slice(200, -200)
    np.testing.assert_allclose(got[..., mid], want[..., mid],
                               rtol=1e-3, atol=1e-3)
    # zero phase: a passband tone comes back unshifted
    t = np.arange(4096)
    tone = np.sin(2 * np.pi * 0.02 * t).astype(np.float32)
    y = np.asarray(ops.filtfilt(b, a, tone))
    lag = np.argmax(np.correlate(y[500:-500], tone[500:-500], "full")) \
        - (len(y) - 1000 - 1)
    assert abs(lag) <= 1


def test_deconvolve_passthrough(rng):
    from scipy.signal import deconvolve as sp_deconvolve

    sig = rng.normal(size=50)
    div = np.array([1.0, 0.5, 0.25])
    q, r = ops.deconvolve(sig, div)
    wq, wr = sp_deconvolve(sig, div)
    np.testing.assert_allclose(q, wq, atol=1e-12)
    np.testing.assert_allclose(r, wr, atol=1e-12)


class TestDesignPassthroughs:
    def test_identity_with_scipy(self):
        import scipy.signal as ss

        np.testing.assert_array_equal(
            ops.ellip(4, 0.5, 40, 0.3, output="sos"),
            ss.ellip(4, 0.5, 40, 0.3, output="sos"))
        np.testing.assert_array_equal(ops.iirnotch(0.2, 30),
                                      ss.iirnotch(0.2, 30))
        np.testing.assert_array_equal(
            ops.remez(33, [0, 0.1, 0.2, 0.5], [1, 0], fs=1.0),
            ss.remez(33, [0, 0.1, 0.2, 0.5], [1, 0], fs=1.0))
        assert ops.buttord(0.2, 0.3, 1, 40) == ss.buttord(0.2, 0.3, 1, 40)
        b, a = ss.butter(4, 0.3)
        np.testing.assert_array_equal(ops.tf2zpk(b, a)[0],
                                      ss.tf2zpk(b, a)[0])

    def test_designed_filter_runs_on_device(self, rng):
        """The loop that matters: scipy-name design -> device filter."""
        sos = np.asarray(ops.ellip(6, 0.2, 60, 0.25, output="sos"))
        x = rng.normal(size=1024).astype(np.float32)
        got = np.asarray(ops.sosfilt(x, sos))
        want = ref_iir.sosfilt(x, sos)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_sosfilt_zi_steady_state(self):
        """Starting a stream from sosfilt_zi * x[0] removes the step
        transient: a constant input yields the DC-gain output from the
        first chunk (scipy's documented zi contract, wired into
        IirStreamState)."""
        import jax.numpy as jnp

        sos = _sos(4, 0.2)
        zi = ops.sosfilt_zi(sos)
        x = np.full(256, 0.7, np.float32)
        st = ops.IirStreamState(jnp.asarray(zi * x[0], jnp.float32))
        _, y = ops.iir_stream_step(st, x, sos)
        np.testing.assert_allclose(np.asarray(y), x, rtol=1e-4,
                                   atol=1e-4)
        # from-rest comparison: the transient IS there without zi
        st0 = ops.iir_stream_init(sos)
        _, y0 = ops.iir_stream_step(st0, x, sos)
        assert abs(float(y0[0]) - 0.7) > 0.1

    def test_lfilter_zi_via_tf2sos(self):
        from scipy.signal import butter, lfilter_zi as sp_zi

        b, a = butter(3, 0.3)
        np.testing.assert_allclose(ops.lfilter_zi(b, a), sp_zi(b, a),
                                   atol=1e-12)


class TestSosfiltfiltPadded:
    @pytest.mark.parametrize("order,wn", [(2, 0.2), (4, 0.3), (6, 0.15)])
    def test_exact_scipy_parity_including_edges(self, rng, order, wn):
        """padtype='odd' reproduces scipy.signal.sosfiltfilt EVERYWHERE
        — the documented edge divergence closes."""
        from scipy.signal import sosfiltfilt as sp_sff

        sos = _sos(order, wn)
        x = rng.normal(size=(2, 700)).astype(np.float32)
        want = sp_sff(sos, x.astype(np.float64), axis=-1)
        got = np.asarray(ops.sosfiltfilt(x, sos, padtype="odd"))
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)

    def test_explicit_padlen_and_reference(self, rng):
        from scipy.signal import sosfiltfilt as sp_sff

        sos = _sos(4, 0.25)
        x = rng.normal(size=300).astype(np.float32)
        want = sp_sff(sos, x.astype(np.float64), padlen=50)
        got = np.asarray(ops.sosfiltfilt(x, sos, padtype="odd",
                                         padlen=50))
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)
        ref = ops.sosfiltfilt(x, sos, padtype="odd", padlen=50,
                              impl="reference")
        np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)

    def test_filtfilt_padded_and_contracts(self, rng):
        from scipy.signal import butter, filtfilt as sp_ff

        b, a = butter(4, 0.3)
        x = rng.normal(size=400).astype(np.float32)
        want = sp_ff(b, a, x.astype(np.float64))
        got = np.asarray(ops.filtfilt(b, a, x, padtype="odd"))
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-4)
        with pytest.raises(ValueError, match="padtype"):
            ops.sosfiltfilt(x, _sos(), padtype="even")
        with pytest.raises(ValueError, match="padlen"):
            ops.sosfiltfilt(np.zeros(10, np.float32), _sos(4, 0.2),
                            padtype="odd")  # default padlen >= n

    def test_decimate_now_matches_scipy_everywhere(self, rng):
        from scipy.signal import decimate as sp_decimate

        x = rng.normal(size=1024).astype(np.float32)
        want = sp_decimate(x.astype(np.float64), 4)
        got = np.asarray(ops.decimate(x, 4))
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_partial_fraction_passthroughs():
    import scipy.signal as ss

    b, a = ss.butter(3, 0.3)
    r, p, k = ops.residuez(b, a)
    wr, wp, wk = ss.residuez(b, a)
    np.testing.assert_allclose(r, wr, atol=1e-12)
    bb, aa = ops.invresz(r, p, k)
    np.testing.assert_allclose(np.real(bb), b, atol=1e-8)


class TestNativeDesign:
    """butter_sos / cheby1_sos are native float64 NumPy as of r4
    (VERDICT r3 item 9): closed-form prototype -> pre-warped band
    transform -> bilinear -> biquad pairing, no scipy in the chain.
    Section pairing/order may differ from scipy's zpk2sos, so parity is
    pinned on the cascade frequency RESPONSE (which any valid pairing
    preserves), not on coefficient bytes."""

    @pytest.mark.parametrize("order", [1, 2, 3, 4, 5, 6, 8])
    @pytest.mark.parametrize("btype,wn", [("lowpass", 0.2),
                                          ("highpass", 0.45),
                                          ("lowpass", 0.95),
                                          ("bandpass", (0.2, 0.4)),
                                          ("bandstop", (0.1, 0.8))])
    def test_butter_response_matches_scipy(self, order, btype, wn):
        from scipy.signal import butter, sosfreqz

        mine = ops.butter_sos(order, np.atleast_1d(wn), btype)
        ref = butter(order, np.atleast_1d(wn), btype, output="sos")
        _, h1 = sosfreqz(mine, worN=512)
        _, h2 = sosfreqz(ref, worN=512)
        np.testing.assert_allclose(h1, h2, atol=1e-10)

    @pytest.mark.parametrize("order", [1, 3, 4, 7])
    @pytest.mark.parametrize("rp", [0.05, 1.0, 3.0])
    @pytest.mark.parametrize("btype,wn", [("lowpass", 0.1),
                                          ("highpass", 0.8),
                                          ("bandpass", (0.2, 0.4))])
    def test_cheby1_response_matches_scipy(self, order, rp, btype, wn):
        from scipy.signal import cheby1, sosfreqz

        mine = ops.cheby1_sos(order, rp, np.atleast_1d(wn), btype)
        ref = cheby1(order, rp, np.atleast_1d(wn), btype, output="sos")
        _, h1 = sosfreqz(mine, worN=512)
        _, h2 = sosfreqz(ref, worN=512)
        np.testing.assert_allclose(h1, h2, atol=1e-10)

    def test_sections_are_stable_and_normalized(self):
        """Every emitted section: a0 == 1 and poles strictly inside the
        unit circle (the associative-scan sosfilt materializes M-power
        products, so marginal poles matter more here than on a CPU)."""
        for sos in (ops.butter_sos(7, 0.3), ops.butter_sos(6, 0.2, "high"),
                    ops.butter_sos(5, [0.2, 0.6], "bandpass"),
                    ops.cheby1_sos(8, 1.0, 0.4),
                    ops.cheby1_sos(3, 0.5, [0.3, 0.7], "bandstop")):
            assert sos.shape[1] == 6
            assert np.all(sos[:, 3] == 1.0)
            for a1, a2 in sos[:, 4:]:
                roots = np.roots([1.0, a1, a2])
                assert np.all(np.abs(roots) < 1.0 - 1e-9)

    def test_btype_aliases_and_errors(self):
        np.testing.assert_allclose(ops.butter_sos(4, 0.3, "low"),
                                   ops.butter_sos(4, 0.3, "lowpass"))
        np.testing.assert_allclose(ops.butter_sos(4, 0.3, "hp"),
                                   ops.butter_sos(4, 0.3, "highpass"))
        with pytest.raises(ValueError):
            ops.butter_sos(4, 1.2)
        with pytest.raises(ValueError):
            ops.butter_sos(4, 0.3, "bandpass")   # needs a pair
        with pytest.raises(ValueError):
            ops.butter_sos(0, 0.3)


def test_zpk_pairing_bounds_intermediate_gain():
    """_zpk_to_sos pairs each pole section with its nearest zero pair
    (scipy zpk2sos discipline, ADVICE r4): the partial-cascade response
    after every section must then stay bounded by the final response's
    scale — an arbitrary construction-order pairing can put a
    resonance-only section early and square the f32 dynamic range on
    high-order narrow-band designs."""
    w = np.linspace(0, np.pi, 4097)
    z = np.exp(1j * w)
    for sos in (ops.cheby1_sos(10, 1, [0.49, 0.51], "bandpass"),
                ops.butter_sos(8, [0.48, 0.52], "bandpass"),
                ops.cheby1_sos(8, 1, 0.3)):
        sos = np.asarray(sos, np.float64)
        H = np.ones_like(z)
        peaks = []
        for s in sos:
            H = (H * (s[0] + s[1] / z + s[2] / z ** 2)
                 / (s[3] + s[4] / z + s[5] / z ** 2))
            peaks.append(np.abs(H).max())
        final = peaks[-1]
        # every partial product bounded by ~the final passband peak:
        # with nearest-zero pairing the measured partials build
        # monotonically (max observed ratio ~1.0); 10x headroom keeps
        # the bound meaningful without pinning the exact pairing
        assert max(peaks) <= 10.0 * final, (peaks, final)


def test_unroll_threshold_boundary_equivalence(rng):
    """The r5 flat-path unroll policy (_IIR_UNROLL_ELEMS) must be
    numerically invisible: shapes just below (scan cascade) and just
    above (unrolled loop) the 2^18-element boundary both match the f64
    oracle."""
    from veles.simd_tpu.ops.iir import _IIR_UNROLL_ELEMS

    sos = ops.butter_sos(6, 0.25)
    n = 2048
    b_under = _IIR_UNROLL_ELEMS // n - 1       # scan-cascade side (127)
    b_over = -(-_IIR_UNROLL_ELEMS // n)        # unrolled side (128)
    for b in (b_under, b_over):
        x = rng.normal(size=(b, n)).astype(np.float32)
        got = np.asarray(ops.sosfilt(x, sos))
        want = np.asarray(ops.sosfilt(x, sos, impl="reference"))
        assert np.abs(got - want).max() < 2e-4, b
