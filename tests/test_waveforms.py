"""Waveform generators + design-verification helpers vs scipy."""

import numpy as np
import pytest

from veles.simd_tpu import ops


class TestChirp:
    @pytest.mark.parametrize("method", ["linear", "quadratic",
                                        "logarithmic", "hyperbolic"])
    def test_matches_scipy(self, method):
        from scipy.signal import chirp as sp_chirp

        t = np.linspace(0, 2.0, 4000)
        want = sp_chirp(t, 5.0, 2.0, 40.0, method=method, phi=30)
        got = np.asarray(ops.chirp(t, 5.0, 2.0, 40.0, method=method,
                                   phi=30))
        np.testing.assert_allclose(got, want, atol=2e-3)

    def test_contracts(self):
        t = np.linspace(0, 1, 16)
        with pytest.raises(ValueError):
            ops.chirp(t, 1, 1, 2, method="cubic")
        with pytest.raises(ValueError):
            ops.chirp(t, 0, 1, 2, method="logarithmic")


@pytest.mark.parametrize("fn,kw", [
    ("square", {"duty": 0.5}), ("square", {"duty": 0.2}),
    ("sawtooth", {"width": 1.0}), ("sawtooth", {"width": 0.5}),
    ("sawtooth", {"width": 0.0})])
def test_square_sawtooth_match_scipy(fn, kw):
    import scipy.signal as ss

    # sample off the discontinuities: the jump sample's side is an
    # f32-vs-f64 phase-rounding coin flip, not a semantic difference
    t = np.linspace(0.013, 40.0, 3000)
    want = getattr(ss, fn)(t, *kw.values())
    got = np.asarray(getattr(ops, fn)(t, *kw.values()))
    err = np.abs(got - want)
    assert np.mean(err > 2e-3) < 0.01  # isolated jump samples only
    assert np.median(err) < 1e-5


def test_gausspulse_matches_scipy():
    from scipy.signal import gausspulse as sp_gausspulse

    t = np.linspace(-0.01, 0.01, 2001)
    want = sp_gausspulse(t, fc=1000, bw=0.5)
    got = np.asarray(ops.gausspulse(t, fc=1000, bw=0.5))
    np.testing.assert_allclose(got, want, atol=1e-4)
    with pytest.raises(ValueError):
        ops.gausspulse(t, fc=-1)


class TestFreqz:
    def test_matches_scipy(self):
        from scipy.signal import butter, freqz as sp_freqz

        b, a = butter(5, 0.3)
        w_ref, h_ref = sp_freqz(b, a, worN=512)
        w, h = ops.freqz(b, a, 512)
        np.testing.assert_allclose(w, w_ref, atol=1e-12)
        np.testing.assert_allclose(h, h_ref, rtol=1e-9)

    def test_fir_only(self):
        h_taps = ops.firwin(21, 0.4)
        w, h = ops.freqz(h_taps)
        assert np.abs(h[0]) == pytest.approx(1.0, abs=1e-3)  # DC gain

    def test_group_delay(self):
        from scipy.signal import butter

        b, a = butter(4, 0.25)
        w, gd = ops.group_delay((b, a), 256)
        assert w.shape == gd.shape == (256,)
        assert np.all(np.isfinite(gd))


class TestPeakPromWidths:
    def test_standalone_prominences(self, rng):
        from scipy.signal import find_peaks as sp_fp
        from scipy.signal import peak_prominences as sp_pp

        x = rng.normal(size=300).astype(np.float32)
        peaks, _ = sp_fp(x.astype(np.float64))
        want_p, want_lb, want_rb = sp_pp(x.astype(np.float64), peaks)
        prom, lb, rb = ops.peak_prominences(x, peaks.astype(np.int32))
        np.testing.assert_allclose(np.asarray(prom), want_p, rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_array_equal(np.asarray(lb), want_lb)
        np.testing.assert_array_equal(np.asarray(rb), want_rb)

    def test_standalone_widths(self, rng):
        from scipy.signal import find_peaks as sp_fp
        from scipy.signal import peak_widths as sp_pw

        x = rng.normal(size=300).astype(np.float32)
        peaks, _ = sp_fp(x.astype(np.float64))
        want = sp_pw(x.astype(np.float64), peaks, rel_height=0.7)
        got = ops.peak_widths(x, peaks.astype(np.int32), rel_height=0.7)
        for g, w_ in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), w_, rtol=1e-3,
                                       atol=1e-3)


def test_chirp_degenerate_constant_frequency():
    """f0 == f1 on log/hyperbolic sweeps is a pure tone, not NaN
    (review r3 finding; scipy special-cases identically)."""
    from scipy.signal import chirp as sp_chirp

    t = np.linspace(0, 1, 500)
    for method in ("logarithmic", "hyperbolic"):
        got = np.asarray(ops.chirp(t, 5.0, 1.0, 5.0, method=method))
        want = sp_chirp(t, 5.0, 1.0, 5.0, method=method)
        assert np.all(np.isfinite(got))
        np.testing.assert_allclose(got, want, atol=1e-4)
    # negative same-sign pair is valid (scipy's rule)
    got = np.asarray(ops.chirp(t, -5.0, 1.0, -40.0, method="hyperbolic"))
    want = sp_chirp(t, -5.0, 1.0, -40.0, method="hyperbolic")
    np.testing.assert_allclose(got, want, atol=2e-3)


def test_duty_width_range_validated():
    t = np.linspace(0, 10, 64)
    with pytest.raises(ValueError):
        ops.square(t, duty=1.3)
    with pytest.raises(ValueError):
        ops.sawtooth(t, width=-0.1)


def test_peak_helpers_accept_padding(rng):
    """-1-padded positions (find_peaks_fixed output) work on BOTH
    backends (review r3 finding)."""
    x = rng.normal(size=200).astype(np.float32)
    pos, _, count, _ = ops.find_peaks_fixed(x, capacity=128)
    pos = np.asarray(pos)
    prom_d = np.asarray(ops.peak_prominences(x, pos)[0])
    prom_r = np.asarray(ops.peak_prominences(x, pos,
                                             impl="reference")[0])
    c = int(count)
    np.testing.assert_allclose(prom_d[:c], prom_r[:c], rtol=1e-4,
                               atol=1e-5)
    w_d = np.asarray(ops.peak_widths(x, pos)[0])
    w_r = np.asarray(ops.peak_widths(x, pos, impl="reference")[0])
    np.testing.assert_allclose(w_d[:c], w_r[:c], rtol=1e-3, atol=1e-3)
    # the padded region itself must come back as fills on BOTH backends
    assert np.all(prom_d[c:] == 0) and np.all(prom_r[c:] == 0)
    assert np.all(w_d[c:] == 0) and np.all(w_r[c:] == 0)
    lb_d = np.asarray(ops.peak_prominences(x, pos)[1])
    lb_r = np.asarray(ops.peak_prominences(x, pos, impl="reference")[1])
    assert np.all(lb_d[c:] == -1) and np.all(lb_r[c:] == -1)
    # out-of-range concrete indices raise on both backends
    bad = np.array([len(x) + 5], np.int32)
    with pytest.raises(ValueError):
        ops.peak_prominences(x, bad)
    with pytest.raises(ValueError):
        ops.peak_widths(x, bad, impl="reference")


def test_square_array_duty_pwm():
    """scipy's canonical PWM pattern: array-valued duty broadcast
    against t (review r3 finding)."""
    import scipy.signal as ss

    t = np.linspace(0.01, 20, 1500)
    duty = 0.5 * (1 + 0.9 * np.sin(2 * np.pi * 0.05 * t))
    want = ss.square(t, duty)
    got = np.asarray(ops.square(t, duty))
    assert np.mean(got != want) < 0.01  # isolated edge samples only


def test_hyperbolic_chirp_opposite_signs():
    from scipy.signal import chirp as sp_chirp

    t = np.linspace(0, 1, 800)
    got = np.asarray(ops.chirp(t, 5.0, 1.0, -40.0, method="hyperbolic"))
    want = sp_chirp(t, 5.0, 1.0, -40.0, method="hyperbolic")
    np.testing.assert_allclose(got, want, atol=2e-3)
    # fc=0 gausspulse is the scipy-valid DC case
    assert np.all(np.isfinite(np.asarray(ops.gausspulse(t, fc=0.0))))
