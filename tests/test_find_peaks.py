"""find_peaks_fixed vs scipy.signal.find_peaks (the definitional
oracle), across every condition family and their combinations."""

import numpy as np
import pytest
from scipy.signal import find_peaks as sp_find_peaks

from veles.simd_tpu import ops


def unpack(res, count_only=False):
    pos, val, count, props = res
    pos, val, count = (np.asarray(pos), np.asarray(val), int(count))
    return pos[:count], val[:count], count, {
        k: np.asarray(v)[:count] for k, v in props.items()}


def check_against_scipy(x, **kw):
    pos, val, count, props = unpack(
        ops.find_peaks_fixed(x, capacity=256, **kw))
    want_pos, want_props = sp_find_peaks(x.astype(np.float64), **kw)
    assert len(want_pos) <= 256, "raise the helper capacity"
    np.testing.assert_array_equal(pos, want_pos)
    np.testing.assert_allclose(val, x[want_pos], rtol=1e-6)
    for name in ("prominences", "widths", "left_ips", "right_ips",
                 "width_heights"):
        if name in want_props and name in props:
            np.testing.assert_allclose(props[name], want_props[name],
                                       rtol=1e-4, atol=1e-4,
                                       err_msg=name)
    for name in ("left_bases", "right_bases"):
        if name in want_props and name in props:
            np.testing.assert_array_equal(props[name],
                                          want_props[name], err_msg=name)
    return pos, props


class TestPlainPeaks:
    def test_simple(self, rng):
        x = rng.normal(size=200).astype(np.float32)
        check_against_scipy(x)

    def test_plateaus_report_midpoint(self):
        x = np.array([0, 1, 1, 1, 0, 2, 2, 0, 3, 0], np.float32)
        check_against_scipy(x)

    def test_edge_plateaus_are_not_peaks(self):
        x = np.array([5, 5, 1, 2, 1, 7, 7], np.float32)
        check_against_scipy(x)

    def test_monotone_has_no_peaks(self):
        x = np.arange(32, dtype=np.float32)
        pos, _, count, _ = unpack(ops.find_peaks_fixed(x))
        assert count == 0 and len(pos) == 0


class TestConditions:
    def test_height_scalar_and_interval(self, rng):
        x = rng.normal(size=300).astype(np.float32)
        check_against_scipy(x, height=0.5)
        check_against_scipy(x, height=(-0.5, 1.0))

    def test_threshold(self, rng):
        x = rng.normal(size=300).astype(np.float32)
        check_against_scipy(x, threshold=0.3)

    def test_distance(self, rng):
        x = rng.normal(size=400).astype(np.float32)
        for d in (2, 5, 20):
            check_against_scipy(x, distance=d)

    def test_prominence(self, rng):
        x = rng.normal(size=300).astype(np.float32)
        check_against_scipy(x, prominence=0.5)
        check_against_scipy(x, prominence=(0.2, 2.0))

    def test_width(self, rng):
        t = np.linspace(0, 6 * np.pi, 600)
        x = (np.sin(t) + 0.1 * np.sin(13 * t)).astype(np.float32)
        check_against_scipy(x, width=5)
        check_against_scipy(x, width=2, rel_height=0.75)

    def test_combined(self, rng):
        x = rng.normal(size=500).astype(np.float32)
        check_against_scipy(x, height=0.0, distance=4, prominence=0.3,
                            width=1.0)

    @pytest.mark.parametrize("seed", range(5))
    def test_fuzz(self, seed):
        g = np.random.default_rng(9000 + seed)
        n = int(g.integers(20, 800))
        x = g.normal(size=n).astype(np.float32)
        if seed % 2:
            # plateau data has exact height ties; scipy's distance
            # suppression breaks ties with an UNSTABLE argsort
            # (quicksort in _select_by_peak_distance), so tie order is
            # unspecified there — exercise prominence/width on plateaus
            # and distance on tie-free data only
            x = np.round(x * 3) / 3
            check_against_scipy(x, prominence=0.2)
        else:
            check_against_scipy(x, prominence=0.2, distance=3)


class TestContract:
    def test_fixed_shapes_and_padding(self, rng):
        x = rng.normal(size=100).astype(np.float32)
        pos, val, count, props = ops.find_peaks_fixed(
            x, capacity=8, prominence=0.0)
        assert pos.shape == (8,) and val.shape == (8,)
        assert all(v.shape == (8,) for v in props.values())
        c = int(count)
        assert np.all(np.asarray(pos)[c:] == -1)

    def test_capacity_truncates(self, rng):
        x = rng.normal(size=400).astype(np.float32)
        pos, _, count, _ = ops.find_peaks_fixed(x, capacity=4)
        assert int(count) <= 4

    def test_jit_and_vmap(self, rng):
        import jax

        x = rng.normal(size=(3, 128)).astype(np.float32)
        fn = jax.vmap(lambda r: ops.find_peaks_fixed(r, capacity=16)[:3])
        pos, val, count = fn(x)
        assert pos.shape == (3, 16)
        for b in range(3):
            want, _ = sp_find_peaks(x[b].astype(np.float64))
            c = int(count[b])
            np.testing.assert_array_equal(np.asarray(pos[b])[:c],
                                          want[:min(len(want), 16)])

    def test_reference_impl_agrees(self, rng):
        x = rng.normal(size=200).astype(np.float32)
        # place the threshold in the widest gap of the prominence
        # distribution: a cutoff within f32 epsilon of some peak's
        # prominence would flip that peak between the f32 device path
        # and the f64 scipy path
        _, all_props = sp_find_peaks(x.astype(np.float64), prominence=0)
        proms = np.sort(all_props["prominences"])
        gaps = np.diff(proms)
        i = int(np.argmax(gaps))
        cut = float((proms[i] + proms[i + 1]) / 2)
        got = unpack(ops.find_peaks_fixed(x, prominence=cut))
        ref = unpack(ops.find_peaks_fixed(x, prominence=cut,
                                          impl="reference"))
        np.testing.assert_array_equal(got[0], ref[0])
        np.testing.assert_allclose(got[3]["prominences"],
                                   ref[3]["prominences"], rtol=1e-4)

    def test_errors(self, rng):
        with pytest.raises(ValueError):
            ops.find_peaks_fixed(np.zeros((2, 50), np.float32))
        with pytest.raises(ValueError):
            ops.find_peaks_fixed(np.zeros(2, np.float32))
        with pytest.raises(ValueError):
            ops.find_peaks_fixed(np.zeros(50, np.float32), distance=0.5)


def test_threshold_sweep_does_not_recompile(rng):
    """Condition VALUES are traced data, not static code: sweeping a
    cutoff must reuse one compiled program (review r3 finding)."""
    from veles.simd_tpu.ops.find_peaks import _find_peaks_xla

    x = rng.normal(size=256).astype(np.float32)
    ops.find_peaks_fixed(x, prominence=0.1, distance=2)
    before = _find_peaks_xla._cache_size()
    for cut in (0.2, 0.3, 0.55):
        ops.find_peaks_fixed(x, prominence=cut, distance=3)
    assert _find_peaks_xla._cache_size() == before


class TestArgrel:
    @pytest.mark.parametrize("order", [1, 3, 10])
    @pytest.mark.parametrize("mode", ["clip", "wrap"])
    def test_matches_scipy(self, rng, order, mode):
        from scipy.signal import argrelmax as sp_amax, argrelmin as sp_amin

        x = rng.normal(size=300).astype(np.float32)
        for ours, theirs in ((ops.argrelmax, sp_amax),
                             (ops.argrelmin, sp_amin)):
            pos, val, count, *_ = ours(x, order=order, mode=mode,
                                       capacity=256)
            c = int(count)
            (want,) = theirs(x.astype(np.float64), order=order, mode=mode)
            np.testing.assert_array_equal(np.asarray(pos)[:c], want)
            np.testing.assert_allclose(np.asarray(val)[:c], x[want],
                                       rtol=1e-6)

    def test_batched_and_reference(self, rng):
        x = rng.normal(size=(3, 100)).astype(np.float32)
        pos, val, count = ops.argrelmax(x, order=2, capacity=64)
        assert pos.shape == (3, 64) and count.shape == (3,)
        ref = ops.argrelmax(x[0], order=2, capacity=64, impl="reference")
        np.testing.assert_array_equal(np.asarray(pos[0]), ref[0])

    def test_contracts(self, rng):
        with pytest.raises(ValueError):
            ops.argrelmax(np.zeros(8, np.float32), order=0)
        with pytest.raises(ValueError):
            ops.argrelmax(np.zeros(8, np.float32), mode="reflect")


def test_traced_condition_values_under_jit(rng):
    """Condition values may be jax tracers: an adaptive (data-dependent)
    height threshold computed INSIDE jit works and matches the same
    threshold applied concretely."""
    import jax
    import jax.numpy as jnp

    x = rng.normal(size=400).astype(np.float32)

    @jax.jit
    def adaptive(sig):
        thresh = jnp.median(sig) + jnp.std(sig)
        return ops.find_peaks_fixed(sig, capacity=64, height=thresh,
                                    distance=jnp.float32(3.0))

    pos, val, count, _ = adaptive(x)
    t = float(np.median(x) + x.std())
    wpos, wval, wcount, _ = ops.find_peaks_fixed(x, capacity=64,
                                                 height=t, distance=3)
    np.testing.assert_array_equal(np.asarray(pos), np.asarray(wpos))
    assert int(count) == int(wcount)


def test_traced_interval_pair_under_jit(rng):
    """(lo, hi) condition pairs of tracers work too (review r3)."""
    import jax
    import jax.numpy as jnp

    x = rng.normal(size=300).astype(np.float32)

    @jax.jit
    def band(sig):
        lo = jnp.median(sig)
        return ops.find_peaks_fixed(sig, capacity=64,
                                    height=(lo, lo + 1.0))

    pos, _, count, _ = band(x)
    lo = float(np.median(x))
    wpos, _, wcount, _ = ops.find_peaks_fixed(x, capacity=64,
                                              height=(lo, lo + 1.0))
    np.testing.assert_array_equal(np.asarray(pos), np.asarray(wpos))
