"""Wavelet coefficient table properties.

The tables are regenerated from the defining equations (see
tools/gen_wavelet_tables.py); these tests pin the mathematical invariants
and the reference's per-family normalization conventions
(src/daubechies.c:34 orthonormal; src/symlets.c:34 and src/coiflets.c:34
normalized to sum = 1).
"""

import os

import numpy as np
import pytest

from veles.simd_tpu import wavelet_data as wd


ALL_FAMILIES = [("daubechies", o) for o in range(2, 77, 2)] + \
               [("symlet", o) for o in range(2, 77, 2)] + \
               [("coiflet", o) for o in range(6, 31, 6)]


@pytest.mark.parametrize("family,order", ALL_FAMILIES)
def test_orthonormality(family, order):
    lo = wd.lowpass(family, order, np.float64)
    # Daubechies rows are stored orthonormal; symlets/coiflets sum to 1.
    h = lo if family == "daubechies" else lo * np.sqrt(2.0)
    # h is now orthonormal: sum h = sqrt(2), sum h[n] h[n+2k] = delta_k
    assert abs(np.sum(h) - np.sqrt(2.0)) < 1e-12
    for k in range(1, order // 2):
        dot = np.dot(h[: order - 2 * k], h[2 * k:])
        assert abs(dot) < 1e-10, (family, order, k)
    assert abs(np.dot(h, h) - 1.0) < 1e-10


def test_known_db8_values():
    # Standard order-8 (db4) scaling coefficients, as published everywhere.
    lo = wd.lowpass("daubechies", 8, np.float64)
    expected = [0.23037781330886, 0.71484657055292, 0.63088076792986,
                -0.02798376941686, -0.18703481171909, 0.03084138183556,
                0.03288301166689, -0.01059740178507]
    np.testing.assert_allclose(lo, expected, atol=1e-12)


def test_normalization_conventions():
    assert abs(np.sum(wd.lowpass("daubechies", 2, np.float64)) - np.sqrt(2)) < 1e-12
    assert abs(np.sum(wd.lowpass("symlet", 2, np.float64)) - 1.0) < 1e-12
    assert abs(np.sum(wd.lowpass("coiflet", 6, np.float64)) - 1.0) < 1e-10


def test_highpass_derivation():
    # highpass[order-1-i] = +lowpass[i] (i odd) / -lowpass[i] (i even),
    # per initialize_highpass_lowpass (src/wavelet.c:187-209).
    hi, lo = wd.highpass_lowpass("daubechies", 8, np.float64)
    for i in range(8):
        expect = lo[i] if i % 2 == 1 else -lo[i]
        assert hi[8 - 1 - i] == expect


def test_stationary_dilation():
    hi1, lo1 = wd.highpass_lowpass("daubechies", 4, np.float64)
    hi2, lo2 = wd.stationary_highpass_lowpass("daubechies", 4, 2, np.float64)
    assert lo2.shape == (8,)
    np.testing.assert_array_equal(lo2[::2], lo1)
    np.testing.assert_array_equal(lo2[1::2], 0)
    # level 1 falls back to the plain pair
    hi0, lo0 = wd.stationary_highpass_lowpass("daubechies", 4, 1, np.float64)
    np.testing.assert_array_equal(lo0, lo1)
    np.testing.assert_array_equal(hi0, hi1)


def test_validate_order_parity():
    # Mirrors wavelet_validate_order semantics (src/wavelet.c:83-98).
    assert wd.validate_order("daubechies", 8)
    assert wd.validate_order("daubechies", 76)
    assert not wd.validate_order("daubechies", 78)
    assert not wd.validate_order("daubechies", 7)
    assert wd.validate_order("coiflet", 6)
    assert wd.validate_order("coiflet", 30)
    assert not wd.validate_order("coiflet", 8)
    assert not wd.validate_order("coiflet", 36)
    assert wd.validate_order("symlet", 2)
    assert not wd.validate_order("symlet", 3)
    assert not wd.validate_order("bogus", 8)


def test_aliases():
    np.testing.assert_array_equal(wd.lowpass("db", 8), wd.lowpass("daubechies", 8))
    np.testing.assert_array_equal(wd.lowpass("sym", 8), wd.lowpass("symlet", 8))
    with pytest.raises(ValueError):
        wd.lowpass("haar", 2)


# ---------------------------------------------------------------------------
# cross-validation against the reference's hand-tabulated C tables
# (src/daubechies.c:34, src/symlets.c:34, src/coiflets.c:34) — the CI loop
# the table regeneration closes (VERDICT round-1 item 6)
# ---------------------------------------------------------------------------

_REF = "/root/reference"


def _ref_rows(fname, cname, rows, cols):
    """Parse a `double kName[rows][cols] = {...}` table from the reference."""
    import re
    src = open(os.path.join(_REF, "src", fname)).read()
    m = re.search(re.escape(cname) + r"\[%d\]\[%d\]\s*=\s*\{(.*?)\n\};"
                  % (rows, cols), src, re.S)
    out = []
    for row in re.findall(r"\{(.*?)\}", m.group(1), re.S):
        out.append(np.array([float(v)
                             for v in re.findall(r"[-+0-9.eE]+", row)]))
    return out


def _ref_tolerance(family, order):
    """Per-family agreement bound vs the reference tabulation.

    Daubechies match bit-exactly. High-order symlets (>= 62) and coif24/30
    deviate by the reference's OWN float64 accumulation / truncation error
    (its rows were computed in double; ours satisfy the defining equations
    to < 1e-20 at 80-digit precision) — the bounds encode the measured
    envelope of that error, not looseness in our tables.
    """
    if family == "daubechies":
        return 1e-14
    if family == "symlet":
        if order <= 60:
            return 2e-8
        if order <= 72:
            return 5e-7
        return 5e-5  # 74: 3.8e-6, 76: 1.7e-5 measured
    # coiflet: 6..18 exact-ish; 24: 1.7e-8; 30: 8.2e-6 measured
    return 2e-5 if order >= 24 else 1e-11


_ALL_FAMILIES = ([("daubechies", o) for o in range(2, 77, 2)]
                 + [("symlet", o) for o in range(2, 77, 2)]
                 + [("coiflet", o) for o in range(6, 31, 6)])


@pytest.mark.skipif(not os.path.isdir(_REF),
                    reason="reference checkout not present")
@pytest.mark.parametrize("family,order", _ALL_FAMILIES)
def test_tables_match_reference(family, order):
    fname, cname, rows, cols = {
        "daubechies": ("daubechies.c", "kDaubechiesD", 38, 76),
        "symlet": ("symlets.c", "kSymletsD", 38, 76),
        "coiflet": ("coiflets.c", "kCoifletsD", 5, 30),
    }[family]
    key = (family, fname)
    cache = test_tables_match_reference.__dict__
    if key not in cache:
        cache[key] = _ref_rows(fname, cname, rows, cols)
    step = 6 if family == "coiflet" else 2
    start = 6 if family == "coiflet" else 2
    row = cache[key][(order - start) // step][:order]
    ours = wd.lowpass(family, order, np.float64)
    np.testing.assert_allclose(ours, row, rtol=0,
                               atol=_ref_tolerance(family, order))
