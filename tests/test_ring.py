"""Ingestion ring buffer (host/ring.py + native vh_ring_*).

Differential contract: arbitrary-size packets in, hop-aligned chunks
out, with chunks + tail reassembling the pushed stream exactly; native
and NumPy-fallback implementations behave identically."""

import subprocess
import sys
import threading

import numpy as np
import pytest

from veles.simd_tpu.host import _native
from veles.simd_tpu.host.ring import RingBuffer


def _roundtrip(ring, packets):
    for p in packets:
        assert ring.push(p) == p.size
    ring.close()
    chunks = [c for c in ring]
    tail = ring.tail()
    return chunks, tail


@pytest.mark.parametrize("sizes", [[64] * 8, [1, 2, 3, 500, 7, 11],
                                   [1000], [128, 0, 128]])
def test_reassembly_exact(rng, sizes):
    data = rng.standard_normal(sum(sizes)).astype(np.float32)
    packets = np.split(data, np.cumsum(sizes)[:-1])
    with RingBuffer(chunk_len=100, capacity=4096) as ring:
        chunks, tail = _roundtrip(ring, packets)
    got = np.concatenate(chunks + [tail]) if chunks or tail.size else tail
    np.testing.assert_array_equal(got, data)
    assert all(c.shape == (100,) for c in chunks)
    assert tail.size == sum(sizes) % 100


def test_int16_push_converts(rng):
    data = rng.integers(-32768, 32767, size=256, dtype=np.int16)
    with RingBuffer(chunk_len=128, capacity=1024) as ring:
        ring.push(data)
        ring.close()
        chunks = [c for c in ring]
    got = np.concatenate(chunks)
    np.testing.assert_array_equal(got, data.astype(np.float32))


def test_overrun_accounting(rng):
    with RingBuffer(chunk_len=64, capacity=128) as ring:
        a = rng.standard_normal(200).astype(np.float32)
        accepted = ring.push(a)
        assert accepted == 128
        assert ring.dropped == 72
        assert ring.available == 128
        # free one chunk -> 64 more fit
        assert ring.pop() is not None
        assert ring.push(a) == 64
        assert ring.dropped == 72 + 136


def test_pop_nonblocking_and_timeout():
    with RingBuffer(chunk_len=64, capacity=256) as ring:
        assert ring.pop() is None            # empty, non-blocking
        assert ring.pop(timeout=0.05) is None  # empty, timed out


def test_tail_requires_close():
    with RingBuffer(chunk_len=64, capacity=256) as ring:
        ring.push(np.zeros(10, np.float32))
        with pytest.raises(RuntimeError):
            ring.tail()
        ring.close()
        assert ring.tail().size == 10


def test_threaded_producer_consumer(rng):
    """Concurrent producer (irregular packets) and consumer (blocking
    pops): every sample arrives exactly once, in order."""
    n = 50_000
    data = rng.standard_normal(n).astype(np.float32)
    ring = RingBuffer(chunk_len=512, capacity=1 << 14)

    # exact producer: the real-time contract is push-and-drop, but this
    # test wants exact reassembly, so the producer retries leftovers
    def produce_exact():
        i = 0
        g = np.random.default_rng(1)
        while i < n:
            k = min(int(g.integers(1, 700)), n - i)
            pkt = data[i:i + k]
            sent = 0
            while sent < k:
                sent += ring.push(pkt[sent:])
            i += k
        ring.close()

    out = []
    t = threading.Thread(target=produce_exact)
    t.start()
    for c in ring:
        out.append(c)
    t.join()
    tail = ring.tail()
    got = np.concatenate(out + ([tail] if tail.size else []))
    np.testing.assert_array_equal(got, data)
    # (dropped counts every rejected offer, so a retrying producer
    # accumulates a nonzero figure by design — no assertion here)
    ring.destroy()


def test_validation():
    with pytest.raises(ValueError):
        RingBuffer(chunk_len=0)
    with pytest.raises(ValueError):
        RingBuffer(chunk_len=64, capacity=32)
    with RingBuffer(chunk_len=8) as ring:
        with pytest.raises(ValueError):
            ring.push(np.zeros((2, 4), np.float32))


def test_feeds_stream_steps(rng):
    """The integration the ring exists for: packets -> chunks -> jitted
    streaming FIR + peaks, equal to the whole-signal ops."""
    from veles.simd_tpu import ops

    n, chunk = 4096, 512
    x = rng.standard_normal(n).astype(np.float32)
    h = rng.standard_normal(31).astype(np.float32)

    ring = RingBuffer(chunk_len=chunk, capacity=1 << 13)
    i = 0
    g = np.random.default_rng(2)
    while i < n:  # irregular packets, self-throttled
        k = min(int(g.integers(1, 900)), n - i)
        sent = 0
        while sent < k:
            sent += ring.push(x[i + sent:i + k])
        i += k
    ring.close()

    fir = ops.fir_stream_init(h)
    pk = ops.peaks_stream_init()
    ys, peaks = [], []
    for c in ring:
        fir, y = ops.fir_stream_step(fir, c, h)
        pk, (pos, val, cnt) = ops.peaks_stream_step(pk, y, capacity=chunk)
        ys.append(np.asarray(y))
        peaks.extend(np.asarray(pos)[:int(cnt)].tolist())
    assert ring.tail().size == 0  # n is a chunk multiple
    got = np.concatenate(ys)
    np.testing.assert_array_equal(got, np.asarray(ops.causal_fir(x, h)))
    wpos, _, wcnt = ops.detect_peaks_fixed(
        np.asarray(ops.causal_fir(x, h)), capacity=n - 2)
    np.testing.assert_array_equal(np.array(peaks),
                                  np.asarray(wpos)[:int(wcnt)])
    ring.destroy()


def test_fallback_matches_native(rng):
    """The NumPy fallback (VELES_NO_NATIVE=1) reassembles identically —
    run in a subprocess so the loader decision is fresh."""
    if not _native.available():
        pytest.skip("native runtime unavailable; fallback is the default")
    code = """
import numpy as np
from veles.simd_tpu.host import _native
from veles.simd_tpu.host.ring import RingBuffer
assert _native.load() is None, "VELES_NO_NATIVE not honored"
rng = np.random.default_rng(7)
data = rng.standard_normal(1234).astype(np.float32)
ring = RingBuffer(chunk_len=100, capacity=2048)
for p in np.split(data, [5, 300, 301, 900]):
    assert ring.push(p) == p.size
ring.close()
chunks = [c for c in ring]
tail = ring.tail()
got = np.concatenate(chunks + [tail])
np.testing.assert_array_equal(got, data)
print("FALLBACK_OK")
"""
    import os
    env = dict(os.environ, VELES_NO_NATIVE="1", JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=env, timeout=120)
    assert "FALLBACK_OK" in r.stdout, r.stderr


def test_tail_with_undrained_chunks(rng):
    """tail() must return everything left — including whole undrained
    chunks — without overflowing (native path used to bound the copy at
    chunk_len while the C side wrote count samples)."""
    data = rng.standard_normal(1000).astype(np.float32)
    with RingBuffer(chunk_len=64, capacity=4096) as ring:
        assert ring.push(data) == 1000
        ring.close()
        t = ring.tail()
    np.testing.assert_array_equal(t, data)


def test_destroy_terminates_iterator():
    ring = RingBuffer(chunk_len=64, capacity=256)
    out = []
    done = threading.Event()

    def consume():
        for c in ring:
            out.append(c)
        done.set()

    t = threading.Thread(target=consume)
    t.start()
    ring.push(np.zeros(64, np.float32))
    ring.destroy()          # error-path cleanup without close()
    assert done.wait(5.0), "iterator did not terminate after destroy()"
    t.join()


def test_ring_churn_recycles_slots():
    """Destroyed rings recycle their slot (free-list + generation bump):
    churn is O(max concurrent rings), stale handles die immediately
    (ADVICE round-1: destroy used to leak the Ring struct and grow the
    handle table without bound)."""
    if _native.load() is None:
        pytest.skip("native host runtime unavailable")
    lib = _native.load()
    handles = set()
    for _ in range(64):
        h = lib.vh_ring_create(256, 64)
        assert h >= 0
        # the retired slot must be recycled: at most 1 live slot means
        # the slot half (low 32 bits) repeats while gens advance
        handles.add(h & 0xffffffff)
        assert lib.vh_ring_destroy(h) == 0
        assert lib.vh_ring_available(h) == -1, "stale handle must die"
    assert len(handles) <= 2, f"slots not recycled: {sorted(handles)}"


def test_ring_python_fallback_pop_wraps(monkeypatch):
    """The NumPy fallback's wrap-aware two-slice pop matches contents
    across the wrap point."""
    monkeypatch.setattr(_native, "load", lambda: None)
    ring = RingBuffer(chunk_len=48, capacity=64)
    assert ring._lib is None, "fallback path not active"
    a = np.arange(48, dtype=np.float32)
    ring.push(a)
    np.testing.assert_array_equal(ring.pop(), a)     # head now at 48
    b = np.arange(100, 148, dtype=np.float32)        # wraps 64-boundary
    ring.push(b)
    np.testing.assert_array_equal(ring.pop(), b)
    ring.close()
