#!/usr/bin/env python
"""Benchmark harness. Prints ONE JSON line for the driver.

Headline metric (BASELINE.md): matrix_multiply float32 N=4096 on one chip,
reported as achieved GFLOPS. ``vs_baseline`` is the ratio against the
north-star target of 50% MXU utilization at the v5e bf16 peak
(0.5 * 197 TFLOPS = 98.5 TFLOPS); >= 1.0 means the target is met.

Measurement method: utils/benchlib.py — the op is iterated inside one jit'd
lax.scan with a data dependency between steps, and a null chain's total is
subtracted (the axon tunnel defers execution past block_until_ready and
adds a ~70 ms round trip, so per-dispatch wall-clocking measures nothing).

``python bench.py --all`` additionally reports the secondary BASELINE
configs on stderr as they come online.
"""

import argparse
import json
import sys

import numpy as np

V5E_BF16_PEAK_GFLOPS = 197_000.0
TARGET_GFLOPS = 0.5 * V5E_BF16_PEAK_GFLOPS


def bench_matmul_4096():
    import jax
    import jax.numpy as jnp

    on_tpu = jax.default_backend() == "tpu"
    n = 4096 if on_tpu else 256  # CPU smoke fallback; driver runs on TPU
    iters = 1024 if on_tpu else 4  # total >> RTT floor so drift can't bias
    k1, k2 = jax.random.split(jax.random.key(0))
    a = jax.random.normal(k1, (n, n), jnp.float32)
    b = jax.random.normal(k2, (n, n), jnp.float32) / jnp.float32(np.sqrt(n))

    from veles.simd_tpu import ops
    from veles.simd_tpu.utils.benchlib import chain_time

    # Chip capability drifts ~2x run-to-run on the shared tunnel; three
    # spaced attempt groups (compiled once, best paired-floor difference)
    # make the report repeatable to ~4%. Tiny null carry: the floor must
    # capture only dispatch/scan/RTT overhead — a full-size null chain
    # would also cancel the HBM pass the matmul legitimately pays,
    # inflating GFLOPS past peak.
    best_dt = chain_time(
        lambda c: ops.matrix_multiply(c, b), a, iters, reps=3,
        null_carry=a[:8, :8], attempts=3 if on_tpu else 1,
        attempt_gap_s=2.0)
    gflops = 2 * n ** 3 / best_dt / 1e9
    return {
        "metric": f"matrix_multiply_f32_n{n}",
        "value": round(gflops, 1),
        "unit": "GFLOPS",
        "vs_baseline": round(gflops / TARGET_GFLOPS, 4),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--all", action="store_true",
                    help="also run secondary configs (reported on stderr)")
    args = ap.parse_args()

    result = bench_matmul_4096()

    if args.all:
        try:
            from veles.simd_tpu.utils.bench_extra import run_secondary
            run_secondary(sys.stderr)
        except ImportError:
            print("secondary configs not yet available", file=sys.stderr)

    print(json.dumps(result))


if __name__ == "__main__":
    main()
