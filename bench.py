#!/usr/bin/env python
"""Benchmark harness. Prints ONE JSON line for the driver.

Headline metric (BASELINE.md): matrix_multiply float32 N=4096 on one chip,
reported as achieved GFLOPS (both impl="xla" dot_general and the hand
Pallas kernel; the headline value is the xla path). ``vs_baseline`` is the
ratio against the north-star target of 50% MXU utilization at the v5e bf16
peak (0.5 * 197 TFLOPS = 98.5 TFLOPS); >= 1.0 means the target is met.

All BASELINE secondary configs (elementwise, convolve, DWT,
normalize+peaks, flagship pipeline, streaming, Welch, feed IO) land in the
same stdout JSON under ``configs``; chain-timed configs carry both the
floor-corrected ``value`` and the uncorrected wall-clock ``raw_value``
lower bound (feed_io is host-wall-clocked, so its single value is already
raw).

Resilience contract (the round-1 failure mode was a transient
``UNAVAILABLE: TPU backend setup/compile error`` crashing the whole run):
the measurement runs in a worker subprocess; the supervisor retries backend
bring-up failures with backoff (full run twice, then a headline-only
attempt), and on persistent failure still prints ONE JSON line with an
``error`` field — the driver always gets parseable output.

Measurement method: utils/benchlib.py — the op is iterated inside one jit'd
lax.scan with a data dependency between steps, and a null chain's total is
subtracted (the axon tunnel defers execution past block_until_ready and
adds a ~70 ms round trip, so per-dispatch wall-clocking measures nothing).
The headline corrected GFLOPS carries a sanity clamp: a value above the
chip's bf16 peak is reported clamped to peak with ``clamped: true`` (the
paired floor can over-correct when the tunnel drifts mid-rep).
"""

import argparse
import json
import math
import os
import subprocess
import sys
import time

V5E_BF16_PEAK_GFLOPS = 197_000.0
TARGET_GFLOPS = 0.5 * V5E_BF16_PEAK_GFLOPS
HEADLINE_METRIC = "matrix_multiply_f32_n4096"


def bench_matmul_4096():
    import jax
    import jax.numpy as jnp
    import numpy as np

    on_tpu = jax.default_backend() == "tpu"
    n = 4096 if on_tpu else 256  # CPU smoke fallback; driver runs on TPU
    iters = 1024 if on_tpu else 4  # total >> RTT floor so drift can't bias
    k1, k2 = jax.random.split(jax.random.key(0))
    a = jax.random.normal(k1, (n, n), jnp.float32)
    b = jax.random.normal(k2, (n, n), jnp.float32) / jnp.float32(np.sqrt(n))

    from veles.simd_tpu import ops
    from veles.simd_tpu.utils.benchlib import chain_stats

    # Chip capability drifts ~2x run-to-run on the shared tunnel; three
    # spaced attempt groups (compiled once, best paired-floor difference)
    # make the report repeatable to ~4%. Tiny null carry: the floor must
    # capture only dispatch/scan/RTT overhead — a full-size null chain
    # would also cancel the HBM pass the matmul legitimately pays,
    # inflating GFLOPS past peak. Both MXU impls run interleaved in the
    # same process so their numbers share one floor and are comparable.
    steps = {"xla": lambda c: ops.matrix_multiply(c, b),
             "pallas": lambda c: ops.matrix_multiply(c, b, impl="pallas")}
    sts = chain_stats(steps, a, iters, reps=3, on_floor="nan",
                      null_carry=a[:8, :8], attempts=3 if on_tpu else 1,
                      attempt_gap_s=2.0)

    def gflops(sec):
        if sec is None or not math.isfinite(sec) or sec <= 0:
            return None
        return round(2 * n ** 3 / sec / 1e9, 1)

    xla_g = gflops(sts["xla"]["sec"])
    raw_g = gflops(sts["xla"]["raw_sec"])
    clamped = xla_g is not None and xla_g > V5E_BF16_PEAK_GFLOPS
    value = min(xla_g, V5E_BF16_PEAK_GFLOPS) if clamped else xla_g
    pallas_g = gflops(sts["pallas"]["sec"])
    # per-attempt corrected values: the artifact shows the spread across
    # chip-state drift (observed ~2x), not just the clamped best point
    attempts_g = [gflops(s) for s in sts["xla"].get("attempt_sec", [])]
    result = {
        "metric": f"matrix_multiply_f32_n{n}",
        "value": value,
        "unit": "GFLOPS",
        "vs_baseline": (round(value / TARGET_GFLOPS, 4)
                        if value is not None else None),
        "raw_value": raw_g,
        "clamped": clamped,
        "attempts": attempts_g,
        "pallas_gflops": pallas_g,
        "pallas_raw_gflops": gflops(sts["pallas"]["raw_sec"]),
        "pallas_attempts": [gflops(s)
                            for s in sts["pallas"].get("attempt_sec", [])],
    }
    # a leg that failed to compile/run carries its reason into the
    # artifact — a null rate alone is indistinguishable from a floored
    # measurement (benchlib failed-leg isolation, r3)
    from veles.simd_tpu.utils.bench_extra import _attach_leg_errors
    _attach_leg_errors(result, sts)
    if xla_g and pallas_g:
        result["pallas_vs_xla"] = round(pallas_g / xla_g, 3)
    return result


class _Tee:
    """Line sink fanning out to several streams (stderr + progress file)."""

    def __init__(self, *streams):
        self.streams = [s for s in streams if s is not None]

    def write(self, data):
        for s in self.streams:
            s.write(data)

    def flush(self):
        for s in self.streams:
            s.flush()


def worker_main(headline_only: bool, progress_path: str | None) -> int:
    import jax

    # The axon TPU plugin on this box overrides JAX_PLATFORMS at import
    # time; a config update after import is the authoritative way to
    # force CPU (for smoke runs / CI boxes without the tunnel).
    if (os.environ.get("VELES_BENCH_CPU") == "1"
            or os.environ.get("JAX_PLATFORMS", "") == "cpu"):
        jax.config.update("jax_platforms", "cpu")

    backend = jax.default_backend()  # forces backend bring-up first
    # Stream every completed piece to the progress file as it lands: if
    # the tunnel dies mid-run, the supervisor merges whatever finished
    # instead of losing the whole record (VERDICT r2 weak #1).
    progress = open(progress_path, "a") if progress_path else None
    result = bench_matmul_4096()
    result["backend"] = backend
    _annotate_ref_avx(result)
    if progress:
        print(json.dumps({"__headline__": result}), file=progress,
              flush=True)
    if not headline_only:
        from veles.simd_tpu.utils.bench_extra import collect_secondary
        result["configs"] = collect_secondary(
            progress=_Tee(sys.stderr, progress))
        for metric, cfg in result["configs"].items():
            _annotate_ref_avx(cfg, metric)
    print(json.dumps(result))
    return 0


_REF_BASELINE_CACHE: list = []  # one-element memo: [configs-or-None]


def _load_ref_baseline():
    if not _REF_BASELINE_CACHE:
        try:
            path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "REF_BASELINE.json")
            with open(path) as f:
                _REF_BASELINE_CACHE.append(json.load(f)["configs"])
        except (OSError, ValueError, KeyError):
            _REF_BASELINE_CACHE.append(None)
    return _REF_BASELINE_CACHE[0]


def _annotate_ref_avx(rec: dict, metric: str | None = None) -> None:
    """Attach the measured reference-AVX baseline ratio in place.

    REF_BASELINE.json (tools/ref_baseline.sh: the reference library
    built -O3 -march=native, timed at these exact shapes) shares metric
    names with the bench configs by construction; when a row matches,
    the record carries ``ref_avx`` (the baseline value) and
    ``vs_ref_avx`` (TPU / AVX — the honest speedup column) directly,
    so the driver artifact is self-contained evidence."""
    ref = _load_ref_baseline()
    if ref is None:
        return
    cfg = ref.get(metric or rec.get("metric", ""))
    value = rec.get("value")
    if not cfg or not isinstance(value, (int, float)) or not cfg.get("value"):
        return
    rec["ref_avx"] = cfg["value"]
    rec["vs_ref_avx"] = round(value / cfg["value"], 1)


def _parse_worker_json(stdout: str):
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


_PROBE_CODE = """
import os, jax
if (os.environ.get("VELES_BENCH_CPU") == "1"
        or os.environ.get("JAX_PLATFORMS", "") == "cpu"):
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
print(jax.default_backend(), float(jnp.ones(()).sum()))
"""


def probe_bringup(timeout_s: float = 90, cmd=None) -> str:
    """'ok' | 'hang' | 'fail: <tail>' — a ~90 s subprocess taxonomy check
    before any full-length attempt. The round-2 failure mode was a
    tunnel that HANGS at backend init: without this probe the supervisor
    burned a 1200 s attempt discovering that, and the driver's budget
    with it."""
    cmd = cmd or [sys.executable, "-c", _PROBE_CODE]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return "hang"
    if proc.returncode == 0:
        return "ok"
    return f"fail: {proc.stderr[-500:]}"


def _read_progress(paths) -> dict:
    """Merge per-attempt progress files into a partial result record."""
    headline, configs = None, {}
    for path in paths:
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "__headline__" in rec:
                headline = rec["__headline__"]
            elif "metric" in rec:
                # the worker annotates ref_avx only at the end of a FULL
                # run; streamed configs arrive bare, so annotate here —
                # a merged partial record must carry the same honest
                # speedup column as a complete one (observed r3: a
                # worker death at the 10th config produced a record
                # with every vs_ref_avx null)
                metric = rec.pop("metric")
                _annotate_ref_avx(rec, metric)
                configs[metric] = rec
    out = dict(headline) if headline else {}
    if configs:
        out["configs"] = configs
    return out


def supervise(headline_only_run: bool = False, *, plans=None,
              worker_cmd=None, probe_cmd=None, probe_timeout_s: float = 90,
              probe_retry_sleep_s: float = 20) -> int:
    """Run the worker with retry/backoff; always print one JSON line.

    Failure taxonomy from rounds 1-2: the tunnel either fails FAST
    (``UNAVAILABLE`` at backend init — worth retrying with backoff) or
    HANGS (bring-up blocks indefinitely). A ~90 s probe subprocess runs
    first: on hang it retries once, then emits the error JSON
    immediately — no full-length attempt is spent discovering a dead
    tunnel. Workers stream each completed piece (headline, then every
    secondary config) to a progress file, so a mid-run death still
    yields a record with everything that finished.

    ``plans``/``worker_cmd``/``probe_cmd`` are injectable for the unit
    tests (fake workers, tiny timeouts)."""
    if plans is None:
        if headline_only_run:
            plans = [(True, 600, 0), (True, 600, 10), (True, 600, 30)]
        else:
            plans = [  # (headline_only, timeout_s, sleep_before_s)
                (False, 1200, 0),
                (False, 1200, 10),
                (True, 480, 30),
            ]

    import shutil
    import tempfile
    progress_dir = tempfile.mkdtemp(prefix="veles_bench_")
    progress_paths = []

    def emit_failure(err: str) -> int:
        partial = _read_progress(progress_paths)
        rec = {"metric": HEADLINE_METRIC, "value": None, "unit": "GFLOPS",
               "vs_baseline": None}
        rec.update(partial)  # headline fields + any completed configs
        rec["error"] = err[-2000:]
        if partial:
            rec["note"] = ("partial record: merged from progress stream "
                           "of failed attempt(s)")
        print(json.dumps(rec))
        return 0

    probe = probe_bringup(probe_timeout_s, cmd=probe_cmd)
    if probe == "hang":
        time.sleep(probe_retry_sleep_s)
        probe = probe_bringup(probe_timeout_s, cmd=probe_cmd)
        if probe == "hang":
            return emit_failure(
                f"backend bring-up hung twice at the {probe_timeout_s}s "
                f"probe; tunnel presumed down, skipping full attempts")
    # A fast probe failure still proceeds: round 1's UNAVAILABLE was
    # transient and the plan list's backoff exists exactly for it.

    last_err = "no attempts ran"
    hung = False
    for headline_only, timeout_s, sleep_s in plans:
        if hung and not headline_only:
            continue  # tunnel hangs: don't repeat a full-length attempt
        if hung:
            # a dead tunnel hangs every attempt; keep the final try short
            # so the error JSON lands inside the driver's own timeout
            timeout_s = min(timeout_s, 300)
        if sleep_s:
            time.sleep(sleep_s)
        ppath = os.path.join(progress_dir,
                             f"attempt{len(progress_paths)}.jsonl")
        progress_paths.append(ppath)
        if worker_cmd is not None:
            cmd = worker_cmd(headline_only, ppath)
        else:
            cmd = [sys.executable, os.path.abspath(__file__), "--worker",
                   "--progress-file", ppath]
            if headline_only:
                cmd.append("--headline-only")
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=timeout_s)
        except subprocess.TimeoutExpired as e:
            hung = True
            last_err = f"worker timed out after {timeout_s}s"
            tail = (e.stderr or b"")
            if isinstance(tail, bytes):
                tail = tail.decode("utf-8", "replace")
            if tail:
                last_err += f"; stderr tail: {tail[-800:]}"
            continue
        sys.stderr.write(proc.stderr[-4000:])
        result = _parse_worker_json(proc.stdout)
        if proc.returncode == 0 and result is not None:
            if headline_only and not headline_only_run:
                result["note"] = ("secondary configs skipped: earlier full "
                                  "attempts failed or hung; headline-only "
                                  "fallback")
                # a failed-but-streaming earlier attempt may still have
                # measured secondary configs worth keeping
                partial = _read_progress(progress_paths[:-1])
                if partial.get("configs"):
                    result.setdefault("configs", partial["configs"])
            print(json.dumps(result))
            # success: the progress stream duplicates the stdout record;
            # on failure the directory is left behind for debugging
            shutil.rmtree(progress_dir, ignore_errors=True)
            return 0
        last_err = (f"worker rc={proc.returncode}; "
                    f"stderr tail: {proc.stderr[-1200:]}")
    # Persistent failure: one parseable line, carrying whatever finished.
    return emit_failure(last_err)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", action="store_true",
                    help="internal: run the measurement in-process")
    ap.add_argument("--headline-only", action="store_true",
                    help="skip the secondary configs")
    ap.add_argument("--all", action="store_true",
                    help="deprecated (secondary configs now run by "
                         "default); kept for compatibility")
    ap.add_argument("--progress-file", default=None,
                    help="internal: worker streams completed pieces here")
    args = ap.parse_args()

    if args.worker:
        sys.exit(worker_main(args.headline_only, args.progress_file))
    sys.exit(supervise(headline_only_run=args.headline_only))


if __name__ == "__main__":
    main()
