#!/usr/bin/env python
"""Benchmark harness. Prints ONE JSON line for the driver.

Headline metric (BASELINE.md): matrix_multiply float32 N=4096 on one chip,
reported as achieved GFLOPS (both impl="xla" dot_general and the hand
Pallas kernel; the headline value is the xla path). ``vs_baseline`` is the
ratio against the north-star target of 50% MXU utilization at the v5e bf16
peak (0.5 * 197 TFLOPS = 98.5 TFLOPS); >= 1.0 means the target is met.

All BASELINE secondary configs (elementwise, convolve, DWT,
normalize+peaks, flagship pipeline, streaming, Welch, feed IO) land in the
same stdout JSON under ``configs``; chain-timed configs carry both the
floor-corrected ``value`` and the uncorrected wall-clock ``raw_value``
lower bound (feed_io is host-wall-clocked, so its single value is already
raw).

Resilience contract (the round-1 failure mode was a transient
``UNAVAILABLE: TPU backend setup/compile error`` crashing the whole run):
the measurement runs in a worker subprocess; the supervisor retries backend
bring-up failures with backoff (full run twice, then a headline-only
attempt), and on persistent failure still prints ONE JSON line with an
``error`` field — the driver always gets parseable output.

Measurement method: utils/benchlib.py — the op is iterated inside one jit'd
lax.scan with a data dependency between steps, and a null chain's total is
subtracted (the axon tunnel defers execution past block_until_ready and
adds a ~70 ms round trip, so per-dispatch wall-clocking measures nothing).
Every GFLOPS figure carries a physics clamp: a value above the chip's
bf16 peak is reported clamped to peak, with the touched field names in
``clamped_fields`` (the paired floor can over-correct when the tunnel
drifts mid-rep; round 3's artifact shipped 146%-of-peak side legs).

The final stdout line is budget-bound (``LINE_BUDGET`` < the driver's
2,000-byte tail capture) via ``emit_record``; the complete unpruned
record lands in ``bench_full_last.json`` beside this file.
"""

import argparse
import json
import math
import os
import subprocess
import sys
import time

V5E_BF16_PEAK_GFLOPS = 197_000.0
TARGET_GFLOPS = 0.5 * V5E_BF16_PEAK_GFLOPS
HEADLINE_METRIC = "matrix_multiply_f32_n4096"

# The driver captures only the LAST 2,000 bytes of stdout; round 3's
# record was ~2.1 KB and lost its head ("metric", "value") to the tail
# window — rc 0, parsed null. Every final print now goes through
# emit_record(), which serializes compactly and prunes lowest-value
# fields until the line fits this budget (headroom under 2,000 for the
# driver's own wrapping). tests/test_bench_line.py pins the contract:
# the full record must json.loads from the line's last 2,000 bytes.
# r5: raised 1780 -> 1845 for the drift_anchor field (VERDICT r4 item
# 2; the field serializes to 62 bytes at full precision), leaving 155 B
# of wrapping margin against the driver's tail window.
LINE_BUDGET = 1845
_CFG_DEFAULT_UNIT = "MSamples/s"


def _clamp_peak_fields(result: dict) -> dict:
    """Physics-bound every GFLOPS figure at the chip's bf16 peak.

    The RTT-floor correction can overshoot when the tunnel drifts
    mid-rep; round 3's driver artifact carried pallas_gflops=287,984 —
    146% of the v5e's 197 TFLOPS peak. The headline ``value`` was
    already clamped; this clamps the rest (side legs, attempt spreads,
    and — defensively — the raw wall-clock bounds, which cannot
    legitimately exceed peak at all) and records which fields were
    touched in ``clamped_fields`` so the artifact never contains a
    physically impossible number without saying so."""
    def cl(v):
        if isinstance(v, (int, float)) and v > V5E_BF16_PEAK_GFLOPS:
            return V5E_BF16_PEAK_GFLOPS, True
        return v, False

    flagged = []
    for key in ("value", "pallas_gflops", "pallas_raw_gflops", "raw_value"):
        v, c = cl(result.get(key))
        if c:
            result[key] = v
            flagged.append(key)
    for key in ("attempts", "pallas_attempts"):
        vals = result.get(key)
        if isinstance(vals, list):
            clamped_list, changed = [], False
            for v in vals:
                v2, c = cl(v)
                clamped_list.append(v2)
                changed |= c
            if changed:
                result[key] = clamped_list
                flagged.append(key)
    if flagged:
        result["clamped_fields"] = flagged
    return result


def _prune_steps(rec: dict):
    """Ordered field-drop ladder for an over-budget line, least
    load-bearing first. The full unpruned record is always preserved in
    ``bench_full_last.json`` beside this file, so pruning only trims the
    driver's one-line view, never the evidence."""
    def all_recs():
        cfgs = rec.get("configs") or {}
        anchor = rec.get("drift_anchor")
        return ([rec] + [c for c in cfgs.values() if isinstance(c, dict)]
                + ([anchor] if isinstance(anchor, dict) else []))

    def trunc_errors(limit):
        for d in all_recs():
            if isinstance(d.get("error"), str):
                d["error"] = d["error"][-limit:]
            le = d.get("leg_errors")
            if isinstance(le, dict):
                d["leg_errors"] = {k: str(v)[-(limit // 2):]
                                   for k, v in le.items()}

    side_keys = ("effective_gbps", "overlap_save_msps",
                 "direct_pallas_msps", "direct_shift_msps", "pallas_msps",
                 "flat_msps", "chunked_msps", "pallas_vs_xla",
                 "chunked_vs_flat", "pipelined_msps")
    # the irreducible per-config facts; everything else may be shed
    essential = ("value", "raw_value", "unit", "vs_ref_avx", "error",
                 "floor_dom")

    def drop_cfg_keys(keys):
        for cfg in (rec.get("configs") or {}).values():
            if isinstance(cfg, dict):
                for k in keys:
                    cfg.pop(k, None)

    def whitelist_cfgs():  # catch-all: bounds unknown future fields too
        for cfg in (rec.get("configs") or {}).values():
            if isinstance(cfg, dict):
                for k in [k for k in cfg if k not in essential]:
                    del cfg[k]

    return [lambda: trunc_errors(300),
            # per-config raw speedups first: derivable by the reader
            # from raw_value + REF_BASELINE.json, unlike what follows
            lambda: drop_cfg_keys(("vs_ref_avx_raw",)),
            lambda: drop_cfg_keys(side_keys),
            lambda: rec.pop("pallas_attempts", None),
            lambda: rec.pop("attempts", None),
            lambda: trunc_errors(80),
            # a (possibly error-carrying) anchor yields before any
            # measured config field does — the full record keeps it
            lambda: rec.pop("drift_anchor", None),
            whitelist_cfgs,
            lambda: drop_cfg_keys(("raw_value",))]


def emit_record(result: dict, budget: int | None = LINE_BUDGET) -> str:
    """Serialize the bench record as ONE compact JSON line under budget.

    Compaction that loses nothing: tight separators, per-config
    ``vs_baseline: null`` dropped (only the headline has a real one),
    and the ubiquitous per-config ``"unit": "MSamples/s"`` hoisted to a
    single top-level ``cfg_unit`` default (consumers:
    tools/speedup_table.py, tools/evidence_table.py). If the line still
    exceeds ``budget``, _prune_steps drops fields in priority order and
    the count lands in ``pruned``. ``budget=None`` skips pruning (the
    worker->supervisor hop has no tail window)."""
    rec = json.loads(json.dumps(result))  # deep copy, JSON-typed
    hoisted = False
    for cfg in (rec.get("configs") or {}).values():
        if not isinstance(cfg, dict):
            continue
        if cfg.get("vs_baseline") is None:
            cfg.pop("vs_baseline", None)
        if cfg.get("unit") == _CFG_DEFAULT_UNIT:
            del cfg["unit"]
            hoisted = True
    if hoisted:
        rec["cfg_unit"] = _CFG_DEFAULT_UNIT
    line = json.dumps(rec, separators=(",", ":"))
    if budget is None or len(line) <= budget:
        return line
    pruned = 0
    for step in _prune_steps(rec):
        step()
        pruned += 1
        line = json.dumps(rec, separators=(",", ":"))
        if len(line) <= budget - 14:  # room for the pruned marker
            break
    rec["pruned"] = pruned
    # Terminal guarantee: an all-errored partial record (12 configs of
    # nulls + error strings) can exhaust the ladder still over budget.
    # Whatever remains, the line MUST fit the driver tail — shed whole
    # trailing configs last (their names at least survive in
    # cfgs_dropped's count, and the full record file keeps everything).
    cfgs = rec.get("configs")
    while (len(json.dumps(rec, separators=(",", ":"))) > budget
           and cfgs):
        cfgs.pop(next(reversed(cfgs)))
        rec["cfgs_dropped"] = rec.get("cfgs_dropped", 0) + 1
    return json.dumps(rec, separators=(",", ":"))


def _write_full_record(result: dict) -> None:
    """Persist the complete unpruned record beside this file. The stdout
    line is budget-bound; this file is the full-detail evidence the
    in-repo tables (tools/evidence_table.py, tools/speedup_table.py)
    render from. Format on both the success and failure paths: the
    compact-but-unpruned shape (units hoisted under ``cfg_unit``), plus
    a wall-clock stamp so a stale file is self-dating. Real supervisor
    runs only — the fake-worker unit tests must never clobber evidence
    (supervise() gates on ``worker_cmd is None``)."""
    try:
        rec = json.loads(emit_record(result, budget=None))
        rec["recorded_unix"] = int(time.time())
        here = os.path.dirname(os.path.abspath(__file__))
        path = os.path.join(here, "bench_full_last.json")
        if rec.get("backend") != "tpu":
            # a CPU smoke run must never rewrite the canonical TPU
            # evidence (its rates are three orders off) — park it
            path = os.path.join(here, "bench_smoke_last.json")
        elif not rec.get("configs"):
            # a headline-only smoke run (or an all-lost failure) must not
            # clobber a full-run record the evidence table renders from —
            # park it beside instead, keeping the anchor/headline evidence
            try:
                with open(path) as f:
                    if json.load(f).get("configs"):
                        path = os.path.join(here,
                                            "bench_headline_last.json")
            except (OSError, json.JSONDecodeError):
                pass
        with open(path, "w") as f:
            json.dump(rec, f, indent=1)
        if os.path.basename(path) != "bench_full_last.json":
            return  # parked records never drive the evidence blocks
    except OSError:
        return  # read-only checkout: the stdout line still lands
    # Regenerate the evidence blocks (BASELINE/README/TPU_EVIDENCE) from
    # the record just written, so a bench run can never leave the repo's
    # quoted numbers stale — the reference's recompute-at-run-time
    # property (tests/benchmark.inc:108-113), demanded by VERDICT r4
    # item 1. Best-effort: a docs problem must never fail a bench run.
    try:
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools"))
        import evidence_table
        evidence_table.update(write=True)
    except (Exception, SystemExit) as e:  # noqa - evidence_table raises
        # SystemExit on missing markers/records; neither may kill the
        # bench before the driver's one stdout line is printed
        print(f"evidence_table auto-update skipped: {e}",
              file=sys.stderr)


def bench_matmul_4096():
    import jax
    import jax.numpy as jnp
    import numpy as np

    on_tpu = jax.default_backend() == "tpu"
    n = 4096 if on_tpu else 256  # CPU smoke fallback; driver runs on TPU
    iters = 1024 if on_tpu else 4  # total >> RTT floor so drift can't bias
    k1, k2 = jax.random.split(jax.random.key(0))
    a = jax.random.normal(k1, (n, n), jnp.float32)
    b = jax.random.normal(k2, (n, n), jnp.float32) / jnp.float32(np.sqrt(n))

    from veles.simd_tpu import ops
    from veles.simd_tpu.utils.benchlib import chain_stats

    # Chip capability drifts ~2x run-to-run on the shared tunnel; three
    # spaced attempt groups (compiled once, best paired-floor difference)
    # make the report repeatable to ~4%. Tiny null carry: the floor must
    # capture only dispatch/scan/RTT overhead — a full-size null chain
    # would also cancel the HBM pass the matmul legitimately pays,
    # inflating GFLOPS past peak. Both MXU impls run interleaved in the
    # same process so their numbers share one floor and are comparable.
    steps = {"xla": lambda c: ops.matrix_multiply(c, b),
             "pallas": lambda c: ops.matrix_multiply(c, b, impl="pallas")}
    sts = chain_stats(steps, a, iters, reps=3, on_floor="nan",
                      null_carry=a[:8, :8], attempts=3 if on_tpu else 1,
                      attempt_gap_s=2.0)

    def gflops(sec):
        if sec is None or not math.isfinite(sec) or sec <= 0:
            return None
        return round(2 * n ** 3 / sec / 1e9, 1)

    def gflops_i(sec):  # attempt spreads: whole GFLOPS (line budget)
        g = gflops(sec)
        return None if g is None else round(g)

    # per-attempt corrected values: the artifact shows the spread across
    # chip-state drift (observed ~2x), not just the clamped best point
    result = {
        "metric": f"matrix_multiply_f32_n{n}",
        "value": gflops(sts["xla"]["sec"]),
        "unit": "GFLOPS",
        "raw_value": gflops(sts["xla"]["raw_sec"]),
        "attempts": [gflops_i(s) for s in sts["xla"].get("attempt_sec", [])],
        "pallas_gflops": gflops(sts["pallas"]["sec"]),
        "pallas_raw_gflops": gflops(sts["pallas"]["raw_sec"]),
        "pallas_attempts": [gflops_i(s)
                            for s in sts["pallas"].get("attempt_sec", [])],
    }
    # a leg that failed to compile/run carries its reason into the
    # artifact — a null rate alone is indistinguishable from a floored
    # measurement (benchlib failed-leg isolation, r3)
    from veles.simd_tpu.utils.bench_extra import _attach_leg_errors
    _attach_leg_errors(result, sts)
    _clamp_peak_fields(result)  # value included: flagged via clamped_fields
    value = result["value"]
    result["vs_baseline"] = (round(value / TARGET_GFLOPS, 3)
                             if value is not None else None)
    if value and result.get("pallas_gflops"):
        # ratio of the clamped figures: both sides physics-bound
        result["pallas_vs_xla"] = round(result["pallas_gflops"] / value, 3)
    return result


def bench_drift_anchor():
    """Fixed canonical kernel timed before everything else
    (VERDICT r4 item 2).

    Absolute rates on the shared tunnel drift ~2x between sessions with
    chip state (ROUND4_NOTES.md), an undisclosed error band on every
    cross-session comparison (the vs_ref columns join a TPU number from
    one session against an AVX number from another; policy-table sweeps
    span sessions too). This anchor — a deterministic 1024^3 f32 matmul
    chain, same shapes and seed every session — pins the session's chip
    state in the artifact itself, so two artifacts compare as anchored
    ratios: rate_a/anchor_a vs rate_b/anchor_b. Reference analogue:
    tests/benchmark.inc:74-113 times baseline and SIMD in the same
    process, so its speedups never cross a chip-state boundary; this is
    the recorded substitute for the property our split-session protocol
    lost."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from veles.simd_tpu import ops
    from veles.simd_tpu.utils.benchlib import chain_stats

    on_tpu = jax.default_backend() == "tpu"
    n = 1024 if on_tpu else 128
    # the chain must dominate the ~100 ms tunnel RTT floor or the
    # correction is all floor: 512 iters (~7 ms of compute) measured
    # raw 11.4 TFLOPS with the corrected figure clamped at peak —
    # meaningless. 32768 iters ≈ 0.5-0.7 s of MXU time per chain.
    iters = 32768 if on_tpu else 4
    k1, k2 = jax.random.split(jax.random.key(7))
    a = jax.random.normal(k1, (n, n), jnp.float32)
    b = jax.random.normal(k2, (n, n), jnp.float32) / jnp.float32(np.sqrt(n))

    def step(c):
        # renormalize the carry: 32k compounding products of a fixed
        # random matrix over/underflow f32 (spectral radius != 1); the
        # mean-square rescale is ~1% of the matmul's FLOPs and keeps
        # the chain finite at any length
        y = ops.matrix_multiply(c, b)
        return y * jax.lax.rsqrt(jnp.mean(y * y) + jnp.float32(1e-30))

    sts = chain_stats({"anchor": step}, a, iters, reps=3, on_floor="nan",
                      null_carry=a[:8, :8],
                      attempts=2 if on_tpu else 1, attempt_gap_s=1.0)

    def g(sec):
        if sec is None or not math.isfinite(sec) or sec <= 0:
            return None
        return round(2 * n ** 3 / sec / 1e9)

    st = sts["anchor"]
    anchor = {"n": n, "gflops": g(st.get("sec")),
              "raw_gflops": g(st.get("raw_sec"))}
    if st.get("error"):
        anchor["error"] = str(st["error"])[-120:]
    # physics clamp (the anchor's keys aren't _clamp_peak_fields' keys):
    # a 1024-chain's floor correction can overshoot like any leg's
    for key in ("gflops", "raw_gflops"):
        v = anchor.get(key)
        if isinstance(v, (int, float)) and v > V5E_BF16_PEAK_GFLOPS:
            anchor[key] = V5E_BF16_PEAK_GFLOPS
            anchor.setdefault("clamped_fields", []).append(key)
    return {k: v for k, v in anchor.items() if v is not None}


class _Tee:
    """Line sink fanning out to several streams (stderr + progress file)."""

    def __init__(self, *streams):
        self.streams = [s for s in streams if s is not None]

    def write(self, data):
        for s in self.streams:
            s.write(data)

    def flush(self):
        for s in self.streams:
            s.flush()


def worker_main(headline_only: bool, progress_path: str | None) -> int:
    import jax

    # The axon TPU plugin on this box overrides JAX_PLATFORMS at import
    # time; a config update after import is the authoritative way to
    # force CPU (for smoke runs / CI boxes without the tunnel).
    if (os.environ.get("VELES_BENCH_CPU") == "1"
            or os.environ.get("JAX_PLATFORMS", "") == "cpu"):
        jax.config.update("jax_platforms", "cpu")

    backend = jax.default_backend()  # forces backend bring-up first
    # Stream every completed piece to the progress file as it lands: if
    # the tunnel dies mid-run, the supervisor merges whatever finished
    # instead of losing the whole record (VERDICT r2 weak #1).
    progress = open(progress_path, "a") if progress_path else None
    try:
        anchor = bench_drift_anchor()
    except Exception as e:  # anchor failure must never sink the bench
        anchor = {"error": str(e)[-120:]}
    result = bench_matmul_4096()
    result["backend"] = backend
    result["drift_anchor"] = anchor
    _annotate_ref_avx(result)
    if progress:
        print(json.dumps({"__headline__": result}), file=progress,
              flush=True)
    if not headline_only:
        from veles.simd_tpu.utils.bench_extra import collect_secondary
        result["configs"] = collect_secondary(
            progress=_Tee(sys.stderr, progress))
        for metric, cfg in result["configs"].items():
            _annotate_ref_avx(cfg, metric)
    # compact but unpruned: the supervisor reparses this hop in full and
    # owns the final budget-bound print
    print(emit_record(result, budget=None))
    return 0


_REF_BASELINE_CACHE: list = []  # one-element memo: [configs-or-None]


def _load_ref_baseline():
    if not _REF_BASELINE_CACHE:
        try:
            path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "REF_BASELINE.json")
            with open(path) as f:
                _REF_BASELINE_CACHE.append(json.load(f)["configs"])
        except (OSError, ValueError, KeyError):
            _REF_BASELINE_CACHE.append(None)
    return _REF_BASELINE_CACHE[0]


def _annotate_ref_avx(rec: dict, metric: str | None = None) -> None:
    """Attach the measured reference-AVX baseline ratios in place.

    REF_BASELINE.json (tools/ref_baseline.sh: the reference library
    built -O3 -march=native, timed at these exact shapes) shares metric
    names with the bench configs by construction; when a row matches,
    the record carries ``vs_ref_avx`` (clamped TPU value / AVX — the
    honest speedup column) and ``vs_ref_avx_raw`` (uncorrected
    wall-clock bound / AVX — the floor speedup no tunnel-drift
    correction can inflate). The baseline value itself is not echoed
    per-config (line budget); it lives in REF_BASELINE.json, joined by
    metric name."""
    ref = _load_ref_baseline()
    if ref is None:
        return
    cfg = ref.get(metric or rec.get("metric", ""))
    value = rec.get("value")
    if not cfg or not isinstance(value, (int, float)) or not cfg.get("value"):
        return
    rec["vs_ref_avx"] = round(value / cfg["value"], 1)
    raw = rec.get("raw_value")
    if isinstance(raw, (int, float)):
        rec["vs_ref_avx_raw"] = round(raw / cfg["value"], 1)
    # VERDICT r3 item 7: where the baseline file carries an _fft_proxy
    # row (the reference's unmeasurable-without-FFTF fast path, proxied
    # by scipy oaconvolve on the same host), report the ceiling-relative
    # speedup too, so vs_ref_avx is explicitly the vs-FLOOR column
    proxy = ref.get((metric or rec.get("metric", "")) + "_fft_proxy")
    if proxy and proxy.get("value"):
        rec["vs_ref_fft"] = round(value / proxy["value"], 1)


def _parse_worker_json(stdout: str):
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


_PROBE_CODE = """
import os, jax
if (os.environ.get("VELES_BENCH_CPU") == "1"
        or os.environ.get("JAX_PLATFORMS", "") == "cpu"):
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
print(jax.default_backend(), float(jnp.ones(()).sum()))
"""


def probe_bringup(timeout_s: float = 90, cmd=None) -> str:
    """'ok' | 'hang' | 'fail: <tail>' — a ~90 s subprocess taxonomy check
    before any full-length attempt. The round-2 failure mode was a
    tunnel that HANGS at backend init: without this probe the supervisor
    burned a 1200 s attempt discovering that, and the driver's budget
    with it."""
    cmd = cmd or [sys.executable, "-c", _PROBE_CODE]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return "hang"
    if proc.returncode == 0:
        return "ok"
    return f"fail: {proc.stderr[-500:]}"


def _read_progress(paths) -> dict:
    """Merge per-attempt progress files into a partial result record."""
    headline, configs = None, {}
    for path in paths:
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "__headline__" in rec:
                headline = rec["__headline__"]
            elif "metric" in rec:
                # the worker annotates ref_avx only at the end of a FULL
                # run; streamed configs arrive bare, so annotate here —
                # a merged partial record must carry the same honest
                # speedup column as a complete one (observed r3: a
                # worker death at the 10th config produced a record
                # with every vs_ref_avx null)
                metric = rec.pop("metric")
                _annotate_ref_avx(rec, metric)
                configs[metric] = rec
    out = dict(headline) if headline else {}
    if configs:
        out["configs"] = configs
    return out


def supervise(headline_only_run: bool = False, *, plans=None,
              worker_cmd=None, probe_cmd=None, probe_timeout_s: float = 90,
              probe_retry_sleep_s: float = 20) -> int:
    """Run the worker with retry/backoff; always print one JSON line.

    Failure taxonomy from rounds 1-2: the tunnel either fails FAST
    (``UNAVAILABLE`` at backend init — worth retrying with backoff) or
    HANGS (bring-up blocks indefinitely). A ~90 s probe subprocess runs
    first: on hang it retries once, then emits the error JSON
    immediately — no full-length attempt is spent discovering a dead
    tunnel. Workers stream each completed piece (headline, then every
    secondary config) to a progress file, so a mid-run death still
    yields a record with everything that finished.

    ``plans``/``worker_cmd``/``probe_cmd`` are injectable for the unit
    tests (fake workers, tiny timeouts)."""
    if plans is None:
        if headline_only_run:
            plans = [(True, 600, 0), (True, 600, 10), (True, 600, 30)]
        else:
            plans = [  # (headline_only, timeout_s, sleep_before_s)
                (False, 1200, 0),
                (False, 1200, 10),
                (True, 480, 30),
            ]

    import shutil
    import tempfile
    progress_dir = tempfile.mkdtemp(prefix="veles_bench_")
    progress_paths = []

    def emit_failure(err: str) -> int:
        partial = _read_progress(progress_paths)
        rec = {"metric": HEADLINE_METRIC, "value": None, "unit": "GFLOPS",
               "vs_baseline": None}
        rec.update(partial)  # headline fields + any completed configs
        rec["error"] = err[-2000:]
        if partial:
            rec["note"] = ("partial record: merged from progress stream "
                           "of failed attempt(s)")
        if worker_cmd is None:  # real run, not a fake-worker unit test
            _write_full_record(rec)
        print(emit_record(rec))
        return 0

    probe = probe_bringup(probe_timeout_s, cmd=probe_cmd)
    if probe == "hang":
        time.sleep(probe_retry_sleep_s)
        probe = probe_bringup(probe_timeout_s, cmd=probe_cmd)
        if probe == "hang":
            return emit_failure(
                f"backend bring-up hung twice at the {probe_timeout_s}s "
                f"probe; tunnel presumed down, skipping full attempts")
    # A fast probe failure still proceeds: round 1's UNAVAILABLE was
    # transient and the plan list's backoff exists exactly for it.

    last_err = "no attempts ran"
    hung = False
    for headline_only, timeout_s, sleep_s in plans:
        if hung and not headline_only:
            continue  # tunnel hangs: don't repeat a full-length attempt
        if hung:
            # a dead tunnel hangs every attempt; keep the final try short
            # so the error JSON lands inside the driver's own timeout
            timeout_s = min(timeout_s, 300)
        if sleep_s:
            time.sleep(sleep_s)
        ppath = os.path.join(progress_dir,
                             f"attempt{len(progress_paths)}.jsonl")
        progress_paths.append(ppath)
        if worker_cmd is not None:
            cmd = worker_cmd(headline_only, ppath)
        else:
            cmd = [sys.executable, os.path.abspath(__file__), "--worker",
                   "--progress-file", ppath]
            if headline_only:
                cmd.append("--headline-only")
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=timeout_s)
        except subprocess.TimeoutExpired as e:
            hung = True
            last_err = f"worker timed out after {timeout_s}s"
            tail = (e.stderr or b"")
            if isinstance(tail, bytes):
                tail = tail.decode("utf-8", "replace")
            if tail:
                last_err += f"; stderr tail: {tail[-800:]}"
            continue
        sys.stderr.write(proc.stderr[-4000:])
        result = _parse_worker_json(proc.stdout)
        if proc.returncode == 0 and result is not None:
            if headline_only and not headline_only_run:
                result["note"] = ("secondary configs skipped: earlier full "
                                  "attempts failed or hung; headline-only "
                                  "fallback")
                # a failed-but-streaming earlier attempt may still have
                # measured secondary configs worth keeping
                partial = _read_progress(progress_paths[:-1])
                if partial.get("configs"):
                    result.setdefault("configs", partial["configs"])
            if worker_cmd is None:  # real run, not a fake-worker test
                _write_full_record(result)
            print(emit_record(result))
            # success: the progress stream duplicates the stdout record;
            # on failure the directory is left behind for debugging
            shutil.rmtree(progress_dir, ignore_errors=True)
            return 0
        last_err = (f"worker rc={proc.returncode}; "
                    f"stderr tail: {proc.stderr[-1200:]}")
    # Persistent failure: one parseable line, carrying whatever finished.
    return emit_failure(last_err)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", action="store_true",
                    help="internal: run the measurement in-process")
    ap.add_argument("--headline-only", action="store_true",
                    help="skip the secondary configs")
    ap.add_argument("--all", action="store_true",
                    help="deprecated (secondary configs now run by "
                         "default); kept for compatibility")
    ap.add_argument("--progress-file", default=None,
                    help="internal: worker streams completed pieces here")
    args = ap.parse_args()

    if args.worker:
        sys.exit(worker_main(args.headline_only, args.progress_file))
    sys.exit(supervise(headline_only_run=args.headline_only))


if __name__ == "__main__":
    main()
